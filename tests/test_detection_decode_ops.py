"""OpTest coverage: detection family + CRF/Viterbi/beam search +
precision_recall (reference unittests: test_prior_box_op.py,
test_box_coder_op.py, test_yolo_box_op.py, test_multiclass_nms_op.py,
test_roi_align_op.py, test_linear_chain_crf_op.py, test_crf_decoding_op.py,
test_beam_search_op.py, test_precision_recall_op.py)."""
import numpy as np

import paddle_tpu  # noqa: F401
from op_test import run_op

R = np.random.RandomState(5)


def test_prior_box():
    feat = np.zeros((1, 8, 2, 2), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)
    out = run_op("prior_box", {"Input": [feat], "Image": [img]},
                 {"min_sizes": [4.0], "max_sizes": [8.0],
                  "aspect_ratios": [2.0], "flip": True, "clip": True,
                  "variances": [0.1, 0.1, 0.2, 0.2]})
    boxes = np.asarray(out["Boxes"][0])
    # priors: ar 1, 2, 0.5 + max-size prior = 4
    assert boxes.shape == (2, 2, 4, 4)
    assert (boxes >= 0).all() and (boxes <= 1).all()
    # first cell center is at offset*step = 8: ar=1 prior is [6,6,10,10]/32
    np.testing.assert_allclose(boxes[0, 0, 0], np.array([6, 6, 10, 10]) / 32,
                               rtol=1e-5)
    var = np.asarray(out["Variances"][0])
    np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator():
    feat = np.zeros((1, 8, 2, 3), np.float32)
    out = run_op("anchor_generator", {"Input": [feat]},
                 {"anchor_sizes": [32.0], "aspect_ratios": [1.0],
                  "stride": [16.0, 16.0]})
    a = np.asarray(out["Anchors"][0])
    assert a.shape == (2, 3, 1, 4)
    np.testing.assert_allclose(a[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_box_coder_roundtrip():
    prior = np.array([[0., 0., 10., 10.], [5., 5., 15., 20.]], np.float32)
    target = np.array([[1., 1., 8., 9.]], np.float32)
    enc = np.asarray(run_op("box_coder",
                            {"PriorBox": [prior], "TargetBox": [target]},
                            {"code_type": "encode_center_size",
                             "box_normalized": True})["OutputBox"][0])
    dec = np.asarray(run_op("box_coder",
                            {"PriorBox": [prior], "TargetBox": [enc]},
                            {"code_type": "decode_center_size",
                             "box_normalized": True})["OutputBox"][0])
    # decode(encode(t)) == t for each prior row
    np.testing.assert_allclose(dec[0], np.tile(target, (2, 1)), rtol=1e-4,
                               atol=1e-4)


def test_iou_similarity_and_box_clip():
    x = np.array([[0., 0., 10., 10.]], np.float32)
    y = np.array([[0., 0., 10., 10.], [5., 5., 15., 15.]], np.float32)
    iou = np.asarray(run_op("iou_similarity", {"X": [x], "Y": [y]},
                            {"box_normalized": True})["Out"][0])
    np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-5)
    np.testing.assert_allclose(iou[0, 1], 25.0 / 175.0, rtol=1e-4)

    boxes = np.array([[-5., -5., 30., 30.]], np.float32)
    iminfo = np.array([[20., 20., 1.]], np.float32)
    out = np.asarray(run_op("box_clip", {"Input": [boxes],
                                         "ImInfo": [iminfo]},
                            {})["Output"][0])
    np.testing.assert_allclose(out[0], [0., 0., 19., 19.])


def test_yolo_box_shapes_and_center():
    an = [10, 13, 16, 30]
    x = np.zeros((1, 2 * 7, 2, 2), np.float32)   # class_num=2
    img = np.array([[64, 64]], np.int64)
    out = run_op("yolo_box", {"X": [x], "ImgSize": [img]},
                 {"anchors": an, "class_num": 2, "conf_thresh": 0.0,
                  "downsample_ratio": 32, "clip_bbox": False})
    boxes = np.asarray(out["Boxes"][0])
    scores = np.asarray(out["Scores"][0])
    assert boxes.shape == (1, 8, 4) and scores.shape == (1, 8, 2)
    # zero logits: sigmoid=0.5 -> center of cell 0 = (0.5/2)*64 = 16
    cx = (boxes[0, 0, 0] + boxes[0, 0, 2]) / 2
    np.testing.assert_allclose(cx, 16.0, rtol=1e-5)


def test_roi_align_and_roi_pool():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0., 0., 3., 3.]], np.float32)
    out = np.asarray(run_op("roi_align", {"X": [x], "ROIs": [rois]},
                            {"pooled_height": 2, "pooled_width": 2,
                             "spatial_scale": 1.0,
                             "sampling_ratio": 2})["Out"][0])
    assert out.shape == (1, 1, 2, 2)
    assert out[0, 0, 0, 0] < out[0, 0, 1, 1]   # increasing ramp preserved

    outp = np.asarray(run_op("roi_pool", {"X": [x], "ROIs": [rois]},
                             {"pooled_height": 2, "pooled_width": 2,
                              "spatial_scale": 1.0})["Out"][0])
    np.testing.assert_allclose(outp[0, 0], [[5., 7.], [13., 15.]])


def test_multiclass_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([[0.0, 0.0, 0.0],          # background
                       [0.9, 0.85, 0.1],         # class 1
                       [0.2, 0.1, 0.8]], np.float32)   # class 2
    out = run_op("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
                 {"score_threshold": 0.3, "nms_threshold": 0.5,
                  "nms_top_k": 3, "keep_top_k": 4, "background_label": 0})
    rows = np.asarray(out["Out"][0])
    n = int(np.asarray(out["NmsRoisNum"][0]))
    assert n == 2   # one box per class (second class-1 box suppressed)
    valid = rows[rows[:, 0] >= 0]
    assert set(valid[:, 0].astype(int)) == {1, 2}
    best1 = valid[valid[:, 0] == 1][0]
    np.testing.assert_allclose(best1[1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(best1[2:], [0, 0, 10, 10], atol=1e-5)


def test_multiclass_nms_index_points_at_kept_boxes():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([[0.0, 0.0, 0.0],
                       [0.9, 0.85, 0.1],
                       [0.2, 0.1, 0.8]], np.float32)
    out = run_op("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
                 {"score_threshold": 0.3, "nms_threshold": 0.5,
                  "nms_top_k": 3, "keep_top_k": 4, "background_label": 0})
    rows = np.asarray(out["Out"][0])
    idx = np.asarray(out["Index"][0])[:, 0]
    n = int(np.asarray(out["NmsRoisNum"][0]))
    for r in range(n):
        # each kept row's box must equal the input box its Index names
        np.testing.assert_allclose(rows[r, 2:], boxes[idx[r]], atol=1e-5)
    assert (idx[n:] == -1).all()    # padding rows carry -1


def test_multiclass_nms_eta_decays_threshold():
    # chain: iou(A,B)=iou(B,C)~0.43, iou(A,C)~0.11. At thr=0.6 all three
    # survive. eta=0.1 decays the threshold after the FIRST keep
    # (0.6 -> 0.06, reference NMSFast: decay only while thr > 0.5), so
    # B and C both overlap kept A above 0.06 and are culled.
    boxes = np.array([[0, 0, 10, 10], [0, 4, 10, 14], [0, 8, 10, 18]],
                     np.float32)
    scores = np.array([[0.9, 0.8, 0.7]], np.float32)
    base = run_op("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
                  {"score_threshold": 0.1, "nms_threshold": 0.6,
                   "nms_top_k": 3, "keep_top_k": 3, "background_label": -1})
    assert int(np.asarray(base["NmsRoisNum"][0])) == 3
    decay = run_op("multiclass_nms", {"BBoxes": [boxes], "Scores": [scores]},
                   {"score_threshold": 0.1, "nms_threshold": 0.6,
                    "nms_top_k": 3, "keep_top_k": 3, "background_label": -1,
                    "nms_eta": 0.1})
    assert int(np.asarray(decay["NmsRoisNum"][0])) == 1


def test_linear_chain_crf_matches_bruteforce():
    b, T, C = 2, 3, 3
    em = R.randn(b, T, C).astype(np.float32)
    trans = R.randn(C + 2, C).astype(np.float32)
    label = R.randint(0, C, (b, T)).astype(np.int64)
    lens = np.array([3, 2], np.int64)
    out = run_op("linear_chain_crf",
                 {"Emission": [em], "Transition": [trans],
                  "Label": [label], "SeqLen": [lens]}, {})
    nll = np.asarray(out["LogLikelihood"][0])

    start, stop, w = trans[0], trans[1], trans[2:]
    for i in range(b):
        L = lens[i]
        # brute-force logZ over all paths
        import itertools
        scores = []
        for path in itertools.product(range(C), repeat=int(L)):
            s = start[path[0]] + em[i, 0, path[0]]
            for t in range(1, L):
                s += w[path[t-1], path[t]] + em[i, t, path[t]]
            s += stop[path[-1]]
            scores.append(s)
        logZ = np.logaddexp.reduce(scores)
        gold = start[label[i, 0]] + em[i, 0, label[i, 0]]
        for t in range(1, L):
            gold += w[label[i, t-1], label[i, t]] + em[i, t, label[i, t]]
        gold += stop[label[i, L-1]]
        np.testing.assert_allclose(nll[i, 0], logZ - gold, rtol=1e-4,
                                   atol=1e-4)


def test_crf_decoding_matches_bruteforce():
    b, T, C = 1, 4, 3
    em = R.randn(b, T, C).astype(np.float32)
    trans = R.randn(C + 2, C).astype(np.float32)
    lens = np.array([4], np.int64)
    path = np.asarray(run_op("crf_decoding",
                             {"Emission": [em], "Transition": [trans],
                              "SeqLen": [lens]}, {})["ViterbiPath"][0])
    start, stop, w = trans[0], trans[1], trans[2:]
    import itertools
    best, best_s = None, -np.inf
    for p in itertools.product(range(C), repeat=T):
        s = start[p[0]] + em[0, 0, p[0]]
        for t in range(1, T):
            s += w[p[t-1], p[t]] + em[0, t, p[t]]
        s += stop[p[-1]]
        if s > best_s:
            best, best_s = p, s
    np.testing.assert_array_equal(path[0], best)


def test_beam_search_and_gather_tree():
    # 1 batch, beam 2, vocab 4
    pre_ids = np.array([[1, 2]], np.int64)
    pre_scores = np.array([[-1.0, -2.0]], np.float32)
    scores = np.array([[[-1.5, -9, -9, -2.0],
                        [-9, -2.5, -9, -9]]], np.float32)  # total log-probs
    out = run_op("beam_search", {"pre_ids": [pre_ids],
                                 "pre_scores": [pre_scores],
                                 "ids": [None], "scores": [scores]},
                 {"beam_size": 2, "end_id": 0})
    sel = np.asarray(out["selected_ids"][0])
    par = np.asarray(out["parent_idx"][0])
    sc = np.asarray(out["selected_scores"][0])
    np.testing.assert_array_equal(sel[0], [0, 3])   # -1.5 then -2.0
    np.testing.assert_array_equal(par[0], [0, 0])
    np.testing.assert_allclose(sc[0], [-1.5, -2.0])

    ids = np.array([[[2, 5]], [[3, 7]], [[1, 4]]], np.int64)  # [T,b,beam]
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
    seq = np.asarray(run_op("gather_tree", {"Ids": [ids],
                                            "Parents": [parents]},
                            {})["Out"][0])
    # beam 0 at t=2: parent chain 0 <- parents[2][0]=0 -> t1 beam0 parent=1
    np.testing.assert_array_equal(seq[:, 0, 0], [5, 3, 1])
    np.testing.assert_array_equal(seq[:, 0, 1], [2, 7, 4])


def test_precision_recall():
    idx = np.array([[0], [1], [1], [2]], np.int32)
    lbl = np.array([[0], [1], [2], [2]], np.int32)
    out = run_op("precision_recall", {"Indices": [idx], "Labels": [lbl]},
                 {"class_number": 3})
    bm = np.asarray(out["BatchMetrics"][0])
    st = np.asarray(out["AccumStatesInfo"][0])
    # class 1: TP=1 FP=1 FN=0; class 2: TP=1 FP=0 FN=1; class 0: TP=1
    np.testing.assert_allclose(st[1, 0], 1)  # TP
    np.testing.assert_allclose(st[1, 1], 1)  # FP
    np.testing.assert_allclose(st[2, 3], 1)  # FN
    # micro: TP=3, FP=1, FN=1 -> P=0.75, R=0.75
    np.testing.assert_allclose(bm[3], 0.75, rtol=1e-5)
    np.testing.assert_allclose(bm[4], 0.75, rtol=1e-5)
