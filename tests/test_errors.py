"""Typed error-code system (reference platform/errors.h + enforce.h +
pybind/exception.cc; reference tests: errors_test.cc, enforce_test.cc)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.framework import errors
from paddle_tpu.framework.errors import ErrorCode


def test_every_code_has_a_class_and_factory():
    for code in ErrorCode:
        if code is ErrorCode.LEGACY:
            continue
        cls = errors.error_class(code)
        assert issubclass(cls, errors.EnforceNotMet)
        assert cls.code == code
        factory = getattr(errors, code.name.title().replace("_", ""))
        e = factory("x=%d", 3)
        assert isinstance(e, cls) and "x=3" in str(e)


def test_builtin_subclassing():
    # each typed error is catchable as the natural python builtin
    # (errors_test.cc checks code round-trip; here the pythonic contract)
    assert issubclass(errors.InvalidArgumentError, ValueError)
    assert issubclass(errors.NotFoundError, KeyError)
    assert issubclass(errors.OutOfRangeError, IndexError)
    assert issubclass(errors.UnimplementedError, NotImplementedError)
    assert issubclass(errors.ResourceExhaustedError, MemoryError)
    assert issubclass(errors.ExecutionTimeoutError, TimeoutError)
    assert issubclass(errors.PermissionDeniedError, PermissionError)
    assert issubclass(errors.FatalError, SystemError)
    assert issubclass(errors.ExternalError, OSError)


def test_enforce_helpers():
    errors.enforce(True, "never raised")
    with pytest.raises(errors.PreconditionNotMetError, match="bad state"):
        errors.enforce(False, "bad state")
    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, errors.InvalidArgument("explicit type"))
    errors.enforce_eq(3, 3)
    with pytest.raises(errors.InvalidArgumentError,
                       match=r"Expected 3 == 4"):
        errors.enforce_eq(3, 4)
    with pytest.raises(errors.InvalidArgumentError, match="rank"):
        errors.enforce_ge(1, 2, "rank")
    assert errors.enforce_not_none(5) == 5
    with pytest.raises(errors.NotFoundError):
        errors.enforce_not_none(None)


def test_op_var_context_in_message():
    e = errors.InvalidArgument("shape mismatch", op="matmul", var="X")
    assert "operator < matmul >" in str(e) and "variable < X >" in str(e)


def test_core_binding_surface():
    # pybind/exception.cc binds exactly these two names on core
    assert fluid.core.EnforceNotMet is errors.EnforceNotMet
    assert fluid.core.EOFException is errors.EOFException


def test_unregistered_op_is_unimplemented():
    from paddle_tpu.ops import registry
    with pytest.raises(errors.UnimplementedError, match="no_such_op"):
        registry.get("no_such_op")
    with pytest.raises(NotImplementedError):  # builtin alias still works
        registry.get("no_such_op")


def test_missing_scope_var_is_not_found():
    from paddle_tpu.framework.scope import Scope
    with pytest.raises(errors.NotFoundError):
        Scope().numpy("nope")


def test_bad_fetch_target_is_not_found():
    from paddle_tpu.fluid import layers
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.mean(x)
    exe = fluid.Executor()
    with pytest.raises(errors.NotFoundError, match="ghost"):
        exe.run(feed={"x": np.zeros((2, 4), np.float32)},
                fetch_list=["ghost"])
    del y
