"""Static sharding & cost analysis (ISSUE 13): build-only unit coverage.

Contract under test: spec propagation infers the right per-var specs from
the one OpSpec rule table; the plan checker rejects illegal compositions
(stage3+tp) and promotes every structural manual-dp fallback cause to a
build-time Finding naming the op/var AND the runtime counter it predicts;
`plan_mode` mirrors the executor's manual-vs-GSPMD decision; and
`predict_cost` derives the exact manual-dp collective sequence from
bucket metadata — all WITHOUT creating an Executor or compiling anything
(the census parity itself is tests/test_cost_parity.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import PlanPoint, check_plan, plan_mode, \
    predict_cost, propagate_sharding
from paddle_tpu.analysis.sharding import FALLBACK_COUNTERS, parse_mesh
from paddle_tpu.fluid import layers
from paddle_tpu.testing import reset_programs


def _build_bucketed_mlp(stage=1, layer_scan=False, bucket_mb=32):
    from paddle_tpu.distributed import fleet
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[16], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, 32, act="tanh")
    loss = layers.mean(layers.square_error_cost(layers.fc(h, 1), y))
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.layer_scan = layer_scan
    if stage:
        s.sharding = True
        s.sharding_stage = stage
    s.fuse_grad_size_in_mb = bucket_mb
    fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-3), s).minimize(loss)
    return fluid.default_main_program(), loss


def _checks(findings, severity=None):
    return {f.check for f in findings
            if severity is None or f.severity == severity}


# ---------------------------------------------------------------------------
# spec propagation
# ---------------------------------------------------------------------------

def test_propagation_batch_spec_flows_and_params_stay_replicated():
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[16], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, 32, act="tanh")
    loss = layers.mean(layers.square_error_cost(layers.fc(h, 1), y))
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = fluid.default_main_program()
    res = propagate_sharding(prog, PlanPoint(mesh_axes={"dp": 2},
                                             batch=16))
    assert res.spec("x") == ("dp", None)          # feed: batch over dp
    assert res.spec(h.name)[0] == "dp"            # activation follows
    assert res.spec(loss.name) == ()              # reduced scalar
    # params replicated without TP rules; their grads mirror them
    w = next(p for p in prog.all_parameters() if p.name.startswith("fc"))
    assert not any(a for a in res.spec(w.name))
    assert not any(a for a in res.spec(w.grad_name()))
    assert not [f for f in res.findings if f.severity == "error"]


def test_propagation_tp_rules_shard_params_and_matmul_contracts():
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.mesh import ShardingRules
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[16], dtype="float32")
    h = layers.fc(x, 32, act="tanh")                # fc_w_0: [16, 32]
    out = layers.fc(h, 16)                          # fc_w_1: [32, 16]
    loss = layers.mean(out)
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rules = ShardingRules([(r"^fc_w_0$", P(None, "tp")),
                           (r"^fc_w_1$", P("tp", None))])
    prog = fluid.default_main_program()
    col_w, row_w = "fc_w_0", "fc_w_1"
    plan = PlanPoint(mesh_axes={"dp": 2, "tp": 2}, param_rules=rules,
                     batch=16)
    res = propagate_sharding(prog, plan)
    assert res.spec(col_w) == (None, "tp")
    assert res.spec(row_w) == ("tp", None)
    # column-parallel fc output carries the tp axis on its last dim
    assert res.spec(h.name) == ("dp", "tp")
    # row-parallel matmul contracts the tp-sharded dim: the propagation
    # predicts the Megatron forward all-reduce
    ar = [e for e in res.events if e["kind"] == "all-reduce"
          and e["origin"] == "matmul_contraction"]
    assert ar, res.events


def test_divisibility_gates_param_sharding():
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.mesh import ShardingRules
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[6], dtype="float32")
    out = layers.fc(x, 3)                # fc_w_0: [6, 3] — 3 % 2 != 0
    loss = layers.mean(out)
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rules = ShardingRules([(r"^fc_w_0$", P(None, "tp"))])
    prog = fluid.default_main_program()
    w = "fc_w_0"
    res = propagate_sharding(prog, PlanPoint(
        mesh_axes={"tp": 2}, param_rules=rules, batch=4))
    assert res.spec(w) == (None, None)   # indivisible dim: replicated


def test_zero_flat_state_specs_seed_dp():
    prog, _ = _build_bucketed_mlp(stage=1)
    res = propagate_sharding(prog, PlanPoint(mesh_axes={"dp": 2},
                                             batch=16))
    flat = [n for n in getattr(prog, "_zero_state_specs", {})]
    assert flat
    for n in flat:
        assert "dp" in res.spec(n), (n, res.spec(n))


# ---------------------------------------------------------------------------
# plan checking: illegal compositions + the fallback matrix
# ---------------------------------------------------------------------------

def test_stage3_plus_tp_rejected_statically():
    prog, _ = _build_bucketed_mlp(stage=3)
    fs = check_plan(prog, PlanPoint(mesh_axes={"dp": 2, "tp": 2}))
    illegal = [f for f in fs if f.check == "illegal_plan"]
    assert illegal and illegal[0].severity == "error"
    assert "stage3+tp" in illegal[0].message
    # the same program on a dp-pure mesh is fine
    fs2 = check_plan(prog, PlanPoint(mesh_axes={"dp": 2}))
    assert not [f for f in fs2 if f.check == "illegal_plan"]


def test_cross_batch_op_under_manual_dp_named_with_counter():
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[16], dtype="float32")
    h, aux = layers.switch_moe(x, num_experts=4, d_ff=32)
    loss = layers.mean(layers.fc(h, 1)) + 0.01 * aux
    paddle.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    prog = fluid.default_main_program()
    fs = check_plan(prog, PlanPoint(mesh_axes={"dp": 2}))
    hits = [f for f in fs if f.check == "manual_dp_fallback"
            and f.op_type == "switch_moe"]
    assert hits, fs
    assert FALLBACK_COUNTERS["cross_batch"] in hits[0].message
    assert hits[0].severity == "warning"
    # strict mode: the planner's hard rejection of the plan point
    strict = [f for f in check_plan(prog, PlanPoint(mesh_axes={"dp": 2}),
                                    strict=True)
              if f.check == "manual_dp_fallback"]
    assert strict and all(f.severity == "error" for f in strict)
    assert plan_mode(prog, PlanPoint(mesh_axes={"dp": 2})) == "gspmd"


def test_selected_rows_fallback_named_with_counter():
    reset_programs(seed=0)
    ids = layers.data(name="ids", shape=[1], dtype="int64")
    emb = layers.embedding(ids, size=(100, 8), is_sparse=True)
    loss = layers.mean(layers.fc(emb, 1))
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = fluid.default_main_program()
    fs = check_plan(prog, PlanPoint(mesh_axes={"dp": 2}))
    hits = [f for f in fs if f.check == "manual_dp_fallback"
            and f.var is not None]
    assert hits, fs
    assert FALLBACK_COUNTERS["selected_rows"] in hits[0].message


def test_indivisible_padding_warned():
    prog, _ = _build_bucketed_mlp(stage=1)
    fs = check_plan(prog, PlanPoint(mesh_axes={"dp": 3}))
    hits = [f for f in fs if f.check == "manual_dp_fallback"
            and "indivisible" in f.message]
    assert hits and FALLBACK_COUNTERS["indivisible_padding"] \
        in hits[0].message
    # pad-to-64 layout: dp=2 divides, no warning
    fs2 = check_plan(prog, PlanPoint(mesh_axes={"dp": 2}))
    assert not [f for f in fs2 if "indivisible" in f.message]


def test_one_cross_batch_table():
    """The runtime decline (parallel/zero.py) and the static lint read the
    SAME cross-batch table — analysis/op_specs.py is the single source."""
    from paddle_tpu.analysis.op_specs import cross_batch_ops
    from paddle_tpu.parallel.zero import _cross_batch_ops
    assert _cross_batch_ops() == cross_batch_ops()
    assert {"switch_moe", "batch_norm", "data_norm",
            "inplace_abn"} <= cross_batch_ops()


def test_parse_mesh():
    assert parse_mesh("dp=2,tp=4") == {"dp": 2, "tp": 4}
    assert parse_mesh("dp=8") == {"dp": 8}


# ---------------------------------------------------------------------------
# plan_mode mirrors the executor's structural decision
# ---------------------------------------------------------------------------

def test_plan_mode_decisions():
    prog, _ = _build_bucketed_mlp(stage=1)
    assert plan_mode(prog, PlanPoint(mesh_axes={"dp": 2})) == "manual"
    assert plan_mode(prog, PlanPoint(mesh_axes={"dp": 2, "tp": 2})) \
        == "gspmd"
    assert plan_mode(prog, PlanPoint(mesh_axes={})) == "single"
    assert plan_mode(prog, PlanPoint(mesh_axes={"dp": 2}, batch=15)) \
        == "gspmd"   # indivisible batch: nothing shards

    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    loss = layers.mean(layers.fc(x, 1))
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    unbucketed = fluid.default_main_program()
    assert plan_mode(unbucketed, PlanPoint(mesh_axes={"dp": 2})) == "gspmd"


# ---------------------------------------------------------------------------
# predict_cost: structural collective derivation, zero compiles
# ---------------------------------------------------------------------------

def test_predict_cost_bucket_all_reduce_bytes():
    prog, loss = _build_bucketed_mlp(stage=0)
    rep = predict_cost(prog, PlanPoint(mesh_axes={"dp": 2}, batch=16),
                       fetch_names=[loss.name])
    assert rep.mode == "manual_dp" and rep.exact
    tot = rep.totals()
    assert set(tot) == {"all-reduce"}
    grad_bytes = 4 * sum(
        int(np.prod(p.shape)) for p in prog.all_parameters()
        if p.trainable)
    n, b = tot["all-reduce"]
    assert n == len(prog._grad_buckets["sync_buckets"]) + 1  # + loss pmean
    assert abs(b - (grad_bytes + 4)) <= 0.01 * grad_bytes


def test_predict_cost_zero1_sequence():
    prog, loss = _build_bucketed_mlp(stage=1)
    rep = predict_cost(prog, PlanPoint(mesh_axes={"dp": 2}, batch=16),
                       fetch_names=[loss.name])
    tot = rep.totals()
    assert set(tot) == {"all-reduce", "all-gather", "reduce-scatter"}
    b = prog._zero_buckets[0]
    assert tot["reduce-scatter"] == (1, b["padded"] * 4 // 2)
    assert tot["all-gather"] == (1, b["padded"] * 4)
    assert tot["all-reduce"] == (1, 4)            # the scalar loss pmean
    # stage-1 memory: flat state halves per device
    assert rep.memory["argument_bytes_per_device"] > 0


def test_predict_cost_gspmd_flagged_inexact():
    prog, loss = _build_bucketed_mlp(stage=1)
    rep = predict_cost(prog, PlanPoint(mesh_axes={"dp": 2, "tp": 2},
                                       batch=16),
                       fetch_names=[loss.name])
    assert rep.mode == "gspmd" and rep.exact is False


def test_predict_cost_to_dict_schema():
    prog, loss = _build_bucketed_mlp(stage=1)
    d = predict_cost(prog, PlanPoint(mesh_axes={"dp": 2}, batch=16),
                     fetch_names=[loss.name]).to_dict()
    assert {"mode", "exact", "collectives", "totals", "memory",
            "findings"} <= set(d)
    for c in d["collectives"]:
        assert {"kind", "count", "nbytes", "origin", "phase",
                "exact"} <= set(c)
    assert {"argument_bytes_per_device", "output_bytes_per_device",
            "state_bytes_read", "state_bytes_written"} \
        <= set(d["memory"])


def test_rng_state_sync_counted_only_in_rolled_bodies():
    from paddle_tpu.analysis.cost import _rng_sync_sites
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import bert

    def build(layer_scan):
        reset_programs(seed=0)
        cfg = bert.BertConfig(vocab_size=64, hidden_size=16, num_layers=2,
                              num_heads=2, intermediate_size=32,
                              max_position=32, seq_len=8,
                              hidden_dropout=0.1, attention_dropout=0.1)
        ids, labels, loss = bert.build_pretrain_program(cfg)
        fleet.init(is_collective=True)
        s = fleet.DistributedStrategy()
        s.layer_scan = layer_scan
        fleet.distributed_optimizer(
            paddle.optimizer.Adam(learning_rate=1e-4), s).minimize(loss)
        return fluid.default_main_program()

    # 3 dropout sites per transformer layer body (attention-prob dropout
    # inside fused_attention + two hidden dropouts)
    assert _rng_sync_sites(build(layer_scan=True)) == 3
    assert _rng_sync_sites(build(layer_scan=False)) == 0
