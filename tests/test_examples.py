"""Every script in examples/ must run green end-to-end (they are the
user-facing quickstart surface; a broken example is a broken front door).
Each runs as a real user subprocess on the virtual CPU mesh."""
import glob
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(ROOT, "examples", "0*.py")))

# Tier-1 rebalance (ISSUE 16): ~51s of real-subprocess example runs; each
# example's API surface is unit-covered, and ci.py shards (which run the
# slow tier) keep the front door green on every CI pass.
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("script", EXAMPLES,
                         ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_runs(script):
    from conftest import cpu_mesh_env
    env = cpu_mesh_env(8)
    r = subprocess.run([sys.executable, script], cwd=ROOT, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (
        f"{os.path.basename(script)} failed:\n{r.stdout[-500:]}\n"
        f"{r.stderr[-1000:]}")
    last = (r.stdout.strip().splitlines() or [""])[-1]
    assert last.startswith("ok"), f"missing final 'ok': {last!r}"
