"""paddle.vision / paddle.text / paddle.dataset surface tests
(reference python/paddle/tests/test_transforms.py, test_datasets.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import transforms as T
from paddle_tpu.vision import datasets as vd
from paddle_tpu import text as ptext


def _img(h=32, w=48, c=3, dtype=np.uint8, seed=0):
    r = np.random.RandomState(seed)
    return r.randint(0, 256, (h, w, c)).astype(dtype)


class TestTransforms:
    def test_resize_shapes(self):
        img = _img()
        assert T.Resize((16, 20))(img).shape == (16, 20, 3)
        out = T.Resize(16)(img)          # shorter side to 16
        assert out.shape == (16, 24, 3)
        near = T.Resize((16, 20), interpolation="nearest")(img)
        assert near.shape == (16, 20, 3)

    def test_resize_bilinear_values(self):
        img = np.arange(16, dtype=np.float32).reshape(4, 4)
        out = T.Resize((2, 2))(img)
        # area-aligned bilinear: averages of 2x2 blocks
        np.testing.assert_allclose(
            out, [[2.5, 4.5], [10.5, 12.5]], atol=1e-5)

    def test_crops_flips_pad(self):
        img = _img()
        assert T.CenterCrop(16)(img).shape == (16, 16, 3)
        assert T.RandomCrop(16)(img).shape == (16, 16, 3)
        assert T.RandomResizedCrop(16)(img).shape == (16, 16, 3)
        assert T.CenterCropResize(24)(img).shape == (24, 24, 3)
        np.testing.assert_array_equal(
            T.RandomHorizontalFlip(1.0)(img), img[:, ::-1])
        np.testing.assert_array_equal(
            T.RandomVerticalFlip(1.0)(img), img[::-1])
        assert T.Pad(2)(img).shape == (36, 52, 3)

    def test_normalize_permute_totensor(self):
        img = _img()
        chw = T.Permute()(img)
        assert chw.shape == (3, 32, 48) and chw.dtype == np.float32
        norm = T.Normalize(mean=127.5, std=127.5)(chw)
        assert abs(float(norm.mean())) < 0.2
        tt = T.ToTensor()(img)
        assert tt.shape == (3, 32, 48) and 0 <= tt.min() <= tt.max() <= 1

    def test_color_ops(self):
        img = _img()
        for t in [T.BrightnessTransform(0.4), T.ContrastTransform(0.4),
                  T.SaturationTransform(0.4), T.HueTransform(0.2),
                  T.ColorJitter(0.2, 0.2, 0.2, 0.1), T.GaussianNoise(0, 5),
                  T.RandomErasing(prob=1.0)]:
            out = t(img)
            assert out.shape == img.shape and out.dtype == img.dtype

    def test_rotate_grayscale(self):
        img = _img()
        assert T.RandomRotate(30)(img).shape == img.shape
        assert T.RandomRotate(30, expand=True)(img).shape[2] == 3
        assert T.Grayscale()(img).shape == (32, 48, 1)
        assert T.Grayscale(3)(img).shape == (32, 48, 3)

    def test_compose(self):
        tr = T.Compose([T.Resize(20), T.CenterCrop(16), T.ToTensor(),
                        T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)])
        out = tr(_img())
        assert out.shape == (3, 16, 16)


class TestVisionDatasets:
    def test_mnist(self):
        ds = vd.MNIST(mode="train")
        img, label = ds[0]
        assert img.shape == (28, 28) and 0 <= label < 10
        assert len(vd.MNIST(mode="test")) < len(ds)

    def test_cifar(self):
        ds = vd.Cifar10(mode="train", transform=T.ToTensor())
        img, label = ds[3]
        assert img.shape == (3, 32, 32) and 0 <= label < 10
        ds100 = vd.Cifar100(mode="test")
        assert max(ds100[i][1] for i in range(len(ds100))) > 9

    def test_flowers_voc(self):
        ds = vd.Flowers(mode="test")
        img, label = ds[0]
        assert img.shape == (64, 64, 3) and 0 <= label < 102
        voc = vd.VOC2012(mode="train")
        img, mask = voc[0]
        assert img.shape == (64, 64, 3) and mask.shape == (64, 64)

    def test_folder(self, tmp_path):
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                np.save(d / f"{i}.npy", _img(8, 8, seed=i))
        ds = vd.DatasetFolder(str(tmp_path))
        assert len(ds) == 4 and ds.classes == ["cat", "dog"]
        img, label = ds[0]
        assert img.shape == (8, 8, 3) and label == 0
        flat = vd.ImageFolder(str(tmp_path))
        assert len(flat) == 4 and flat[0][0].shape == (8, 8, 3)

    def test_dataloader_integration(self):
        from paddle_tpu.io import DataLoader
        ds = vd.MNIST(mode="test", transform=T.Compose([T.ToTensor()]))
        loader = DataLoader(ds, batch_size=16, shuffle=True, num_workers=0)
        imgs, labels = next(iter(loader))
        assert tuple(np.asarray(imgs).shape) == (16, 1, 28, 28)
        assert len(np.asarray(labels)) == 16


class TestVisionModels:
    def test_forward_shapes(self):
        import paddle_tpu.vision as V
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32))
        for factory in (lambda: V.mobilenet_v1(scale=0.25, num_classes=7),
                        lambda: V.mobilenet_v2(scale=0.25, num_classes=7)):
            m = factory()
            m.eval()
            out = m(x)
            assert tuple(out.shape) == (2, 7)

    def test_vgg_small(self):
        import paddle_tpu.vision as V
        m = V.vgg11(num_classes=5)
        m.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(1, 3, 224, 224)
            .astype(np.float32))
        assert tuple(m(x).shape) == (1, 5)

    def test_resnet_variants_exist(self):
        import paddle_tpu.vision as V
        assert V.resnet34 and V.resnet152 and V.LeNet


class TestTextDatasets:
    def test_uci_housing(self):
        tr = ptext.UCIHousing(mode="train")
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert abs(float(np.stack([tr[i][0] for i in
                                   range(len(tr))]).mean())) < 0.1

    def test_imdb_imikolov(self):
        ds = ptext.Imdb(mode="train")
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        ng = ptext.Imikolov(mode="train", window_size=5)
        assert len(ng[0]) == 5

    def test_movielens_wmt(self):
        ml = ptext.Movielens(mode="train")
        s = ml[0]
        assert len(s) == 8 and isinstance(s[-1], float)
        wmt = ptext.WMT16(mode="test")
        src, trg, nxt = wmt[0]
        assert len(trg) == len(nxt)
        assert trg[0] == 0 and nxt[-1] == 1   # bos/eos framing

    def test_conll05(self):
        ds = ptext.Conll05st(mode="test")
        words, pred, mark, labels = ds[0]
        assert len(words) == len(mark) == len(labels)
        assert mark.sum() == 1

    def test_viterbi_decode(self):
        r = np.random.RandomState(0)
        pot = r.randn(2, 6, 4).astype(np.float32)
        trans = r.randn(4, 4).astype(np.float32)
        path = ptext.viterbi_decode(pot, trans,
                                    lengths=np.array([6, 4], np.int64))
        arr = np.asarray(path.numpy())
        assert arr.shape == (2, 6)
        # brute-force check for batch 0
        best, best_score = None, -1e30
        import itertools
        for seq in itertools.product(range(4), repeat=6):
            sc = pot[0, 0, seq[0]] + sum(
                trans[seq[t - 1], seq[t]] + pot[0, t, seq[t]]
                for t in range(1, 6))
            if sc > best_score:
                best_score, best = sc, seq
        np.testing.assert_array_equal(arr[0], best)


class TestLegacyDatasetModule:
    def test_readers(self):
        import paddle_tpu.dataset as D
        x, y = next(D.uci_housing.train()())
        assert x.shape == (13,)
        img, label = next(D.mnist.train()())
        assert img.shape == (784,) and -1 <= img.min()
        sample = next(D.cifar.train10()())
        assert sample[0].shape == (3072,)
        doc, lab = next(D.imdb.train()())
        assert isinstance(doc, list) and lab in (0, 1)
        assert len(next(D.imikolov.train()())) == 5
        assert D.movielens.max_user_id() == 6040
        src, trg, nxt = next(D.wmt16.train()())
        assert trg[0] == 0
        # DatasetFactory still lives on the same namespace
        assert D.DatasetFactory

    def test_import_styles(self):
        import paddle_tpu.dataset.mnist as m
        assert m.train
