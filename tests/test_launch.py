"""Supervised gang launcher (distributed/launch.py): env contract,
deadline-bounded rendezvous, fail-fast sibling kill, bounded elastic
restart, and stale-heartbeat hang detection.

The worker scripts are plain stdlib python (no jax import), so every test
here is seconds, not minutes — the supervisor runs IN-PROCESS via
launch(argv) and the gang members are real subprocesses."""
import os
import signal
import sys
import threading
import time

import numpy as np  # noqa: F401  (conftest import parity)
import pytest

from paddle_tpu.distributed.launch import launch, plan_gang


# --- env contract (pure unit) --------------------------------------------

def test_plan_gang_env_contract():
    """One endpoint PER PROCESS and world-size-true PADDLE_TRAINERS_NUM /
    JAX_NUM_PROCESSES — the two fields the fire-and-forget launcher got
    wrong for single-host multi-process gangs."""
    plans = plan_gang(["10.0.0.1", "10.0.0.2"], 6170, 2)
    assert len(plans) == 4
    eps = plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert eps == ["10.0.0.1:6170", "10.0.0.1:6171",
                   "10.0.0.2:6170", "10.0.0.2:6171"]
    for rank, p in enumerate(plans):
        assert p["PADDLE_TRAINER_ID"] == str(rank)
        assert p["PADDLE_TRAINERS_NUM"] == "4"
        assert p["JAX_NUM_PROCESSES"] == "4"
        assert p["JAX_PROCESS_ID"] == str(rank)
        assert p["PADDLE_CURRENT_ENDPOINT"] == eps[rank]
        assert p["PADDLE_TRAINER_ENDPOINTS"] == ",".join(eps)
    # the jax coordinator port sits ABOVE every trainer endpoint port
    assert plans[0]["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:6174"


def test_plan_gang_single_host_multi_process():
    """nnodes==1 with --nproc_per_node=4: 4 endpoints and world size 4
    (the old code emitted ONE endpoint and JAX_NUM_PROCESSES=1)."""
    plans = plan_gang(["127.0.0.1"], 6170, 4)
    assert len(plans) == 4
    assert len(plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 4
    assert plans[0]["PADDLE_TRAINERS_NUM"] == "4"
    assert plans[0]["JAX_NUM_PROCESSES"] == "4"


def test_plan_gang_elastic_shrink():
    """world=M < full keeps the FIRST M ranks with an M-wide contract —
    the elastic-restart relaunch shape."""
    plans = plan_gang(["127.0.0.1"], 6170, 4, world=3)
    assert len(plans) == 3
    assert plans[0]["PADDLE_TRAINERS_NUM"] == "3"
    assert plans[0]["JAX_NUM_PROCESSES"] == "3"
    assert len(plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 3


# --- supervisor behavior (real gangs of stdlib workers) -------------------

def _worker(tmp_path, body: str) -> str:
    """Write a stdlib-only worker script; `body` sees rank/world/restart."""
    path = str(tmp_path / "worker.py")
    with open(path, "w") as f:
        f.write(
            "import os, sys, time\n"
            "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "world = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
            "restart = int(os.environ.get('PADDLE_ELASTIC_RESTART', '0'))\n"
            + body)
    return path


def _launch(argv) -> int:
    with pytest.raises(SystemExit) as e:
        launch(argv)
    return int(e.value.code or 0)


def test_rendezvous_straggler_kills_gang_typed(tmp_path, monkeypatch,
                                               capsys):
    """A worker that never checks in past FLAGS_rendezvous_deadline_ms
    fails the whole launch with the typed DeadlineExceededError — never a
    hang, never a wedged survivor."""
    script = _worker(tmp_path, "time.sleep(0.2)\n")
    monkeypatch.setenv("PADDLE_LAUNCH_STALL_RANKS", "1")
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7301",
                  "--rendezvous_deadline_ms", "1500",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    assert rc != 0
    assert elapsed < 30, elapsed
    err = capsys.readouterr().err
    assert "DeadlineExceeded" in err, err


def test_fail_fast_sibling_kill(tmp_path):
    """One worker exiting non-zero must take the gang down within the
    grace window: the surviving sibling (asleep for 600s) is terminated,
    not left to wedge in its next collective."""
    pid_file = str(tmp_path / "sibling.pid")
    script = _worker(tmp_path, f"""
if rank == 0:
    time.sleep(0.3)
    sys.exit(7)
with open({pid_file!r}, "w") as f:
    f.write(str(os.getpid()))
time.sleep(600)
""")
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7311",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    assert rc == 7
    assert elapsed < 60, elapsed
    with open(pid_file) as f:
        sibling = int(f.read())
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(sibling, 0)
        except ProcessLookupError:
            break                           # sibling is gone: fail-fast held
        time.sleep(0.05)
    else:
        os.kill(sibling, signal.SIGKILL)
        pytest.fail("sibling survived the fail-fast kill")


def test_elastic_restart_at_surviving_world_size(tmp_path):
    """--elastic_restarts: after a worker loss the gang relaunches at the
    SURVIVING world size with PADDLE_ELASTIC_RESTART incremented, and a
    clean second life exits 0."""
    log = str(tmp_path / "lives.log")
    script = _worker(tmp_path, f"""
with open({log!r}, "a") as f:
    f.write(f"restart={{restart}} world={{world}} rank={{rank}}\\n")
if world == 2 and rank == 0:
    sys.exit(3)          # first life: rank 0 dies immediately
time.sleep(0.3 if world == 1 else 600)
""")
    rc = _launch(["--nproc_per_node", "2", "--port", "7321",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", "--elastic_restarts", "2",
                  script])
    assert rc == 0
    with open(log) as f:
        lives = f.read().splitlines()
    assert "restart=0 world=2 rank=0" in lives, lives
    assert "restart=1 world=1 rank=0" in lives, lives


def test_stale_heartbeat_detected_as_hung(tmp_path):
    """A worker that stops beating (SIGSTOP — the OOM-thrash / wedged-C
    simulation) is detected via its stale heartbeat file and fails the
    gang instead of wedging it."""
    pid_file = str(tmp_path / "victim.pid")
    script = _worker(tmp_path, f"""
if rank == 0:
    with open({pid_file!r}, "w") as f:
        f.write(str(os.getpid()))
time.sleep(600)
""")

    def stopper():
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.exists(pid_file):
                with open(pid_file) as f:
                    txt = f.read()
                if txt:
                    os.kill(int(txt), signal.SIGSTOP)
                    return
            time.sleep(0.05)

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7331",
                  "--rendezvous_deadline_ms", "20000",
                  "--heartbeat_timeout_ms", "2000",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    t.join(timeout=5)
    assert rc != 0
    assert elapsed < 120, elapsed
