"""Supervised gang launcher (distributed/launch.py): env contract,
deadline-bounded rendezvous, fail-fast sibling kill, bounded elastic
restart, and stale-heartbeat hang detection.

The worker scripts are plain stdlib python (no jax import), so every test
here is seconds, not minutes — the supervisor runs IN-PROCESS via
launch(argv) and the gang members are real subprocesses. The pod-scope
drills fabricate REAL-SCHEMA flight dumps + heartbeat JSON from stdlib
(the dump/heartbeat formats are file contracts, not imports), so
supervisor dump collection and straggler naming are tested in seconds;
the jax-worker version of the same drill is scripts/pod_trace.py --smoke
(CI)."""
import json
import os
import signal
import sys
import threading
import time

import numpy as np  # noqa: F401  (conftest import parity)
import pytest

from paddle_tpu.distributed.launch import launch, plan_gang


# --- env contract (pure unit) --------------------------------------------

def test_plan_gang_env_contract():
    """One endpoint PER PROCESS and world-size-true PADDLE_TRAINERS_NUM /
    JAX_NUM_PROCESSES — the two fields the fire-and-forget launcher got
    wrong for single-host multi-process gangs."""
    plans = plan_gang(["10.0.0.1", "10.0.0.2"], 6170, 2)
    assert len(plans) == 4
    eps = plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert eps == ["10.0.0.1:6170", "10.0.0.1:6171",
                   "10.0.0.2:6170", "10.0.0.2:6171"]
    for rank, p in enumerate(plans):
        assert p["PADDLE_TRAINER_ID"] == str(rank)
        assert p["PADDLE_TRAINERS_NUM"] == "4"
        assert p["JAX_NUM_PROCESSES"] == "4"
        assert p["JAX_PROCESS_ID"] == str(rank)
        assert p["PADDLE_CURRENT_ENDPOINT"] == eps[rank]
        assert p["PADDLE_TRAINER_ENDPOINTS"] == ",".join(eps)
    # the jax coordinator port sits ABOVE every trainer endpoint port
    assert plans[0]["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:6174"


def test_plan_gang_single_host_multi_process():
    """nnodes==1 with --nproc_per_node=4: 4 endpoints and world size 4
    (the old code emitted ONE endpoint and JAX_NUM_PROCESSES=1)."""
    plans = plan_gang(["127.0.0.1"], 6170, 4)
    assert len(plans) == 4
    assert len(plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 4
    assert plans[0]["PADDLE_TRAINERS_NUM"] == "4"
    assert plans[0]["JAX_NUM_PROCESSES"] == "4"


def test_plan_gang_elastic_shrink():
    """world=M < full keeps the FIRST M ranks with an M-wide contract —
    the elastic-restart relaunch shape."""
    plans = plan_gang(["127.0.0.1"], 6170, 4, world=3)
    assert len(plans) == 3
    assert plans[0]["PADDLE_TRAINERS_NUM"] == "3"
    assert plans[0]["JAX_NUM_PROCESSES"] == "3"
    assert len(plans[0]["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 3


# --- supervisor behavior (real gangs of stdlib workers) -------------------

def _worker(tmp_path, body: str) -> str:
    """Write a stdlib-only worker script; `body` sees rank/world/restart."""
    path = str(tmp_path / "worker.py")
    with open(path, "w") as f:
        f.write(
            "import os, sys, time\n"
            "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "world = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
            "restart = int(os.environ.get('PADDLE_ELASTIC_RESTART', '0'))\n"
            + body)
    return path


def _launch(argv) -> int:
    with pytest.raises(SystemExit) as e:
        launch(argv)
    return int(e.value.code or 0)


def test_rendezvous_straggler_kills_gang_typed(tmp_path, monkeypatch,
                                               capsys):
    """A worker that never checks in past FLAGS_rendezvous_deadline_ms
    fails the whole launch with the typed DeadlineExceededError — never a
    hang, never a wedged survivor."""
    script = _worker(tmp_path, "time.sleep(0.2)\n")
    monkeypatch.setenv("PADDLE_LAUNCH_STALL_RANKS", "1")
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7301",
                  "--rendezvous_deadline_ms", "1500",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    assert rc != 0
    assert elapsed < 30, elapsed
    err = capsys.readouterr().err
    assert "DeadlineExceeded" in err, err


def test_fail_fast_sibling_kill(tmp_path):
    """One worker exiting non-zero must take the gang down within the
    grace window: the surviving sibling (asleep for 600s) is terminated,
    not left to wedge in its next collective."""
    pid_file = str(tmp_path / "sibling.pid")
    script = _worker(tmp_path, f"""
if rank == 0:
    time.sleep(0.3)
    sys.exit(7)
with open({pid_file!r}, "w") as f:
    f.write(str(os.getpid()))
time.sleep(600)
""")
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7311",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    assert rc == 7
    assert elapsed < 60, elapsed
    with open(pid_file) as f:
        sibling = int(f.read())
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(sibling, 0)
        except ProcessLookupError:
            break                           # sibling is gone: fail-fast held
        time.sleep(0.05)
    else:
        os.kill(sibling, signal.SIGKILL)
        pytest.fail("sibling survived the fail-fast kill")


def test_elastic_restart_at_surviving_world_size(tmp_path):
    """--elastic_restarts: after a worker loss the gang relaunches at the
    SURVIVING world size with PADDLE_ELASTIC_RESTART incremented, and a
    clean second life exits 0."""
    log = str(tmp_path / "lives.log")
    script = _worker(tmp_path, f"""
with open({log!r}, "a") as f:
    f.write(f"restart={{restart}} world={{world}} rank={{rank}}\\n")
if world == 2 and rank == 0:
    sys.exit(3)          # first life: rank 0 dies immediately
time.sleep(0.3 if world == 1 else 600)
""")
    rc = _launch(["--nproc_per_node", "2", "--port", "7321",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", "--elastic_restarts", "2",
                  script])
    assert rc == 0
    with open(log) as f:
        lives = f.read().splitlines()
    assert "restart=0 world=2 rank=0" in lives, lives
    assert "restart=1 world=1 rank=0" in lives, lives


def test_stale_heartbeat_detected_as_hung(tmp_path):
    """A worker that stops beating (SIGSTOP — the OOM-thrash / wedged-C
    simulation) is detected via its stale heartbeat file and fails the
    gang instead of wedging it."""
    pid_file = str(tmp_path / "victim.pid")
    script = _worker(tmp_path, f"""
if rank == 0:
    with open({pid_file!r}, "w") as f:
        f.write(str(os.getpid()))
time.sleep(600)
""")

    def stopper():
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.exists(pid_file):
                with open(pid_file) as f:
                    txt = f.read()
                if txt:
                    os.kill(int(txt), signal.SIGSTOP)
                    return
            time.sleep(0.05)

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    t0 = time.monotonic()
    rc = _launch(["--nproc_per_node", "2", "--port", "7331",
                  "--rendezvous_deadline_ms", "20000",
                  "--heartbeat_timeout_ms", "2000",
                  "--grace_period_s", "1", script])
    elapsed = time.monotonic() - t0
    t.join(timeout=5)
    assert rc != 0
    assert elapsed < 120, elapsed


# --- pod-scope drills (stdlib workers writing the real file contracts) -----

# Worker body: per "step", update the launcher heartbeat file with the
# JSON step note (the observability/flight.py contract) and overwrite a
# real-schema flight dump — each rank on its own fake trace-clock epoch,
# so the drill exercises podscope's clock alignment too. The dump's
# TIMELINE is fabricated deterministically (wall position = a fixed base +
# this rank's cumulative step time), so cross-rank skew reflects only the
# per-rank step_ms the drill chose — real spawn/scheduler jitter between
# the worker processes cannot flake the suspect verdict; the real sleeps
# below only pace the LIVE heartbeat behavior the supervisor watches.
_POD_WORKER_BODY = """
import json
step_ms = {step_ms}
nsteps = {nsteps}
hb = os.environ.get("PADDLE_LAUNCH_HEARTBEAT_FILE")
dump_dir = os.environ["FLAGS_flight_dump_dir"]
os.makedirs(dump_dir, exist_ok=True)
epoch = 7e9 * (rank + 1)                 # per-process trace-clock epoch
# shared fabricated wall t0: the supervisor's launch instant — identical
# across ranks AND recent enough for collection's staleness cutoff
base_wall = float(os.environ["PADDLE_LAUNCH_START_US"])
cum_us = 0.0
steps, events = [], []
for step in range(1, nsteps + 1):
    dur_ms = step_ms[rank] if rank < len(step_ms) else step_ms[-1]
    time.sleep(dur_ms / 1000.0)          # pace the live heartbeats
    t0 = epoch + cum_us
    cum_us += dur_ms * 1000.0
    ts = epoch + cum_us                  # trace-clock arrival
    events.append({{"name": "collective", "ph": "i", "cat": "collective",
                    "ts": ts, "tid": 1, "pid": os.getpid(),
                    "args": {{"kind": "__bucket_sync__", "step": step,
                              "bucket": 0, "seq": 0,
                              "key": "s%d.b0.q0" % step}}}})
    steps.append({{"step": step, "exe": 1, "t0_us": t0, "t1_us": ts,
                   "status": "ok", "metrics_delta": {{}}}})
    if hb:
        with open(hb + ".tmp", "w") as f:
            json.dump({{"pid": os.getpid(), "step": step,
                        "step_ms": dur_ms}}, f)
        os.replace(hb + ".tmp", hb)
    payload = {{"format": 1, "reason": "drill", "rank": rank,
                "world": world, "role": "trainer", "pid": os.getpid(),
                "wall_time": (base_wall + cum_us) / 1e6,
                "clock": {{"wall_time_us": base_wall + cum_us,
                           "trace_ts_us": epoch + cum_us}},
                "steps": steps, "trace_events": events, "metrics": {{}}}}
    path = os.path.join(dump_dir,
                        "flight_r%d_%d_drill_1.json" % (rank, os.getpid()))
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)
"""


def test_gang_failure_names_straggler_live_and_in_report(tmp_path, capsys):
    """Induced straggler drill: rank 1 crawls (400 ms/step) while rank 0
    finishes its steps and exits non-zero. The supervisor must name rank 1
    LIVE in the gang-failure output (heartbeat last-step spread) AND the
    collected pod straggler report must score rank 1 as the suspect."""
    pod_dir = str(tmp_path / "pod")
    script = _worker(
        tmp_path,
        _POD_WORKER_BODY.format(step_ms=[10, 400], nsteps=8)
        + "if rank == 0:\n"
          "    time.sleep(2.0)   # let the crawling rank 1 record steps\n"
          "    sys.exit(5)\n"
          "time.sleep(600)\n")
    rc = _launch(["--nproc_per_node", "2", "--port", "7341",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", "--collect-dumps",
                  "--pod_dump_dir", pod_dir, script])
    assert rc == 5
    out = capsys.readouterr().out
    assert "suspected straggler: rank 1" in out, out
    # post-hoc: the pod collection merged both ranks' dumps and the report
    # names the same rank
    with open(os.path.join(pod_dir, "straggler_report.json")) as f:
        report = json.load(f)
    assert report["suspect"] == 1, report["ranks"]
    assert report["ranks"]["1"]["last_step"] < report["gang_max_step"]
    # the heartbeat snapshot rode into the pod dir for postmortems
    with open(os.path.join(pod_dir, "heartbeats.json")) as f:
        hb = json.load(f)
    assert hb["status"] == "failed" and hb["world"] == 2
    assert hb["heartbeats"]["0"]["step"] == 8


def test_collect_dumps_clean_exit_round_trip(tmp_path, capsys):
    """--collect-dumps on a CLEAN gang exit: per-rank dumps gathered into
    the pod dir, ONE merged timeline with both rank lanes and >= 1
    cross-rank collective flow pair, and a straggler report that names
    NOBODY (symmetric ranks)."""
    pod_dir = str(tmp_path / "pod")
    script = _worker(tmp_path,
                     _POD_WORKER_BODY.format(step_ms=[10, 10], nsteps=3))
    rc = _launch(["--nproc_per_node", "2", "--port", "7351",
                  "--rendezvous_deadline_ms", "20000",
                  "--grace_period_s", "1", "--collect-dumps",
                  "--pod_dump_dir", pod_dir, script])
    assert rc == 0
    out = capsys.readouterr().out
    assert "pod dump: 2 rank dump(s)" in out, out
    # raw per-rank dumps were copied in (rank-tagged names, no collision)
    raw = sorted(f for f in os.listdir(pod_dir)
                 if f.startswith("flight_r"))
    assert len(raw) == 2 and raw[0].startswith("flight_r0_") \
        and raw[1].startswith("flight_r1_"), raw
    with open(os.path.join(pod_dir, "pod_trace.json")) as f:
        merged = json.load(f)
    evs = merged["traceEvents"]
    lanes = {e["pid"] for e in evs if e.get("name") == "process_name"}
    assert lanes == {0, 1}
    flows = [e for e in evs if e.get("cat") == "pod_collective"]
    assert {e["ph"] for e in flows} >= {"s", "f"}
    assert len({e["pid"] for e in flows}) == 2, "flows never cross lanes"
    with open(os.path.join(pod_dir, "straggler_report.json")) as f:
        report = json.load(f)
    assert report["suspect"] is None, report["ranks"]
    assert report["summary"]["collective_keys_matched"] >= 1
