"""Rolled-layer step programs (parallel/transforms.apply_layer_scan).

The N isomorphic per-layer segments of a deep model collapse into ONE
__layer_scan__ op whose lowering is a lax.scan over [L]-stacked weights.
Contract under test: rolled == unrolled to float tolerance for loss AND
updated params (with remat and dropout, under dp/tp meshes), graceful
fallback on non-isomorphic segments, stacked-param checkpoint round-trip
through io.save/load (including loading an UNROLLED checkpoint into a
rolled program), and the compile-stats win — the rolled step's
optimized-HLO instruction count must be <= 40% of the unrolled step's.

Tests here deliberately merge related assertions: every BERT build costs
an XLA compile, and the tier-1 suite runs under a hard wall-clock budget.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.framework.scope import global_scope
from paddle_tpu.parallel.transforms import apply_layer_scan
from paddle_tpu.testing import reset_programs

# Adam's g/sqrt(v) normalization amplifies reassociation-level float
# noise in near-zero gradients; atol floors those elements while rtol
# 1e-5 governs everything of magnitude.
TOL = dict(rtol=1e-5, atol=1e-7)


def _build_bert(rolled, num_layers=4, dropout=0.0, remat=False, seed=0,
                lr=0.01):
    from paddle_tpu.models import bert
    reset_programs(seed)
    cfg = bert.BertConfig(vocab_size=256, hidden_size=16,
                          num_layers=num_layers, num_heads=2,
                          intermediate_size=32, max_position=32, seq_len=8,
                          hidden_dropout=dropout, attention_dropout=dropout)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    if rolled:
        consumed = apply_layer_scan(
            fluid.default_main_program(), loss._layer_checkpoints,
            remat=remat, startup_program=fluid.default_startup_program())
        assert consumed == loss._layer_checkpoints[:-1]
    paddle.optimizer.Adam(learning_rate=lr).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    feed = {"input_ids": rng.randint(0, cfg.vocab_size,
                                     (4, cfg.seq_len)).astype(np.int64),
            "mlm_labels": rng.randint(0, cfg.vocab_size,
                                      (4, cfg.seq_len, 1)).astype(np.int64)}
    return exe, feed, loss, cfg


def test_roll_structure_and_fleet_knob_cheap():
    """Tier-1 structural coverage (build-only, no XLA compiles): the roll
    replaces the 4 layer segments with one __layer_scan__ op, creates
    [L]-stacked Parameters (per-layer ones leave the program), appends
    the startup stack ops, and the fleet strategy knob engages the pass
    (composing with recompute: the scan lands inside the prologue
    __segment__ with the interior boundaries dropped from the checkpoint
    list). Numeric parity lives in the slow-marked tests below."""
    from paddle_tpu.models import bert
    reset_programs(0)
    cfg = bert.BertConfig(vocab_size=256, hidden_size=16, num_layers=4,
                          num_heads=2, intermediate_size=32,
                          max_position=32, seq_len=8,
                          hidden_dropout=0.1, attention_dropout=0.1)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    prog = fluid.default_main_program()
    n_before = len(prog.global_block().ops)
    consumed = apply_layer_scan(
        prog, loss._layer_checkpoints,
        startup_program=fluid.default_startup_program())
    assert consumed == loss._layer_checkpoints[:-1]
    gb = prog.global_block()
    scan_ops = [op for op in gb.ops if op.type == "__layer_scan__"]
    assert len(scan_ops) == 1
    assert len(gb.ops) * 3 < n_before
    assert scan_ops[0].attrs["num_layers"] == 4
    # per-layer rng seeds (dropout) ride the scan as xs
    assert any(s is not None and len(s) == 4
               for s in scan_ops[0].attrs["layer_seeds"])
    sv = gb.var("enc0_attn_qkv_w@LAYERS")
    assert sv.persistable and tuple(sv.shape)[0] == 4
    assert not gb.has_var("enc1_attn_qkv_w")         # per-layer params gone
    assert prog._layer_stacks["enc0_attn_qkv_w@LAYERS"] == [
        f"enc{i}_attn_qkv_w" for i in range(4)]
    sb = fluid.default_startup_program().global_block()
    stacks = [op for op in sb.ops if op.type == "stack"]
    assert stacks and all(op.outputs["Y"][0].endswith("@LAYERS")
                          for op in stacks)
    assert not sb.vars["enc1_attn_qkv_w"].persistable

    # fleet knob + recompute composition, build-only
    from paddle_tpu.distributed import fleet
    reset_programs(0)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.layer_scan = True
    s.recompute = True
    s.recompute_configs = {"checkpoints": list(loss._layer_checkpoints)}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=0.01), s)
    opt.minimize(loss)
    types = []
    for op in fluid.default_main_program().global_block().ops:
        types.append(op.type)
        for od in op.attrs.get("sub_ops", []):
            types.append(od["type"])
            if od["type"] == "__layer_scan__":
                assert od["attrs"]["remat"] is True   # remat-of-scan-body
    assert "__layer_scan__" in types and "__segment__" in types

    # clone(for_test) must flip is_test at EVERY sub_ops nesting depth
    # (dropout descs inside the scan inside the recompute segment)
    test_prog = fluid.default_main_program().clone(for_test=True)

    def _check_descs(sub_ops, depth=0):
        flipped = 0
        for od in sub_ops:
            if "is_test" in od["attrs"]:
                assert od["attrs"]["is_test"] is True, (depth, od["type"])
                flipped += 1
            flipped += _check_descs(od["attrs"].get("sub_ops", []),
                                    depth + 1)
        return flipped

    n_flipped = sum(_check_descs(op.attrs.get("sub_ops", []))
                    for op in test_prog.global_block().ops)
    assert n_flipped > 0        # the dropout descs were actually reached


@pytest.mark.slow
def test_rolled_bert_matches_unrolled():
    """Acceptance: rolled tiny-BERT (4 layers, dropout ON) matches the
    unrolled program's losses over two steps BIT-FOR-BIT (per-layer rng
    seeds ride the scan as xs and fold into the run key exactly as the
    unrolled ops fold their static seeds, so dropout masks agree), every
    per-layer updated param slice matches to tolerance, remat=True
    (remat-of-the-scan-body) changes nothing, the rolled program is
    several times smaller, and the layer scan nests inside the k-step
    run_steps training-loop scan."""
    exe, feed, loss, cfg = _build_bert(False, dropout=0.1)
    n_ops_unrolled = len(fluid.default_main_program().global_block().ops)
    lu = [np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
          for _ in range(2)]
    params_u = {}
    for i in range(cfg.num_layers):
        for stem in ("attn_qkv_w", "attn_proj_w", "ffn_in_w", "ffn_out_w",
                     "ln1_scale", "ln2_bias"):
            n = f"enc{i}_{stem}"
            params_u[n] = np.asarray(global_scope().find(n)).copy()
    su = np.asarray(exe.run_steps(3, feed=feed, fetch_list=[loss])[0])

    exe, feed, loss, cfg = _build_bert(True, dropout=0.1)
    gb = fluid.default_main_program().global_block()
    assert "__layer_scan__" in [op.type for op in gb.ops]
    n_ops_rolled = len(gb.ops)
    assert n_ops_rolled * 3 < n_ops_unrolled, (n_ops_rolled, n_ops_unrolled)
    lr_ = [np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
           for _ in range(2)]
    np.testing.assert_array_equal(lr_[0], lu[0])    # bit-for-bit
    np.testing.assert_allclose(lr_[1], lu[1], **TOL)
    for i in range(cfg.num_layers):
        for stem in ("attn_qkv_w", "attn_proj_w", "ffn_in_w", "ffn_out_w",
                     "ln1_scale", "ln2_bias"):
            stacked = np.asarray(
                global_scope().find(f"enc0_{stem}@LAYERS"))
            np.testing.assert_allclose(stacked[i],
                                       params_u[f"enc{i}_{stem}"], **TOL)
    sr = np.asarray(exe.run_steps(3, feed=feed, fetch_list=[loss])[0])
    np.testing.assert_allclose(sr.ravel(), su.ravel(), **TOL)

    exe, feed, loss, _ = _build_bert(True, dropout=0.1, remat=True)
    lm = [np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
          for _ in range(2)]
    np.testing.assert_allclose(lm, lu, **TOL)


@pytest.mark.slow
def test_rolled_gpt_matches_unrolled():
    """GPT rolls through its new _layer_checkpoints annotation; the tied
    wte stays a loop-invariant (consumed by prologue AND epilogue, never
    stacked)."""
    from paddle_tpu.models import gpt

    def build(rolled):
        reset_programs(0)
        cfg = gpt.GPTConfig(vocab_size=256, hidden_size=16, num_layers=4,
                            num_heads=2, intermediate_size=32,
                            max_position=32, seq_len=8, hidden_dropout=0.0,
                            attention_dropout=0.0)
        tokens, loss = gpt.build_lm_program(cfg)
        if rolled:
            assert apply_layer_scan(
                fluid.default_main_program(), loss._layer_checkpoints,
                startup_program=fluid.default_startup_program()) is not None
        paddle.optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(2)
        feed = {"tokens": rng.randint(0, cfg.vocab_size,
                                      (4, cfg.seq_len)).astype(np.int64)}
        return [np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                for _ in range(2)]

    ref = build(False)
    got = build(True)                 # rolled build last: scope assertions
    np.testing.assert_allclose(got, ref, **TOL)
    assert global_scope().find("wte") is not None      # tied table unstacked
    assert global_scope().find("dec0_attn_qkv_w@LAYERS") is not None


@pytest.mark.slow
def test_rolled_hlo_instruction_count_under_40pct():
    """Acceptance: the rolled step's optimized-HLO instruction count is
    <= 40% of the unrolled step's at 8 tiny-BERT layers (the rolled count
    is ~constant in L — the layer body compiles once). Audited through
    the public Executor.compiled_hlo."""
    def n_instr(txt):
        return sum(1 for line in txt.splitlines() if " = " in line)

    exe, feed, loss, _ = _build_bert(False, num_layers=8)
    unrolled = n_instr(exe.compiled_hlo(feed, [loss]))
    exe, feed, loss, _ = _build_bert(True, num_layers=8)
    rolled = n_instr(exe.compiled_hlo(feed, [loss]))
    assert rolled <= 0.40 * unrolled, (rolled, unrolled)


@pytest.mark.slow
def test_rolled_matches_unrolled_under_dp_and_tp_mesh():
    """Stacked params compose with SPMD: the [L] axis stays unsharded and
    the per-layer TP specs shift one dim right (parallel/mesh.py), so a
    dp=2 and a tp=2 mesh both train to the same losses as unrolled."""
    import jax
    from paddle_tpu.models import bert
    from paddle_tpu.parallel import DistConfig, attach, build_mesh

    for axes in ({"dp": 2}, {"tp": 2}):
        losses = {}
        for rolled in (False, True):
            exe, feed, loss, _ = _build_bert(rolled)
            mesh = build_mesh(devices=jax.devices()[:2], **axes)
            attach(fluid.default_main_program(),
                   DistConfig(mesh=mesh,
                              param_rules=bert.tp_sharding_rules()))
            losses[rolled] = [
                np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                for _ in range(2)]
        np.testing.assert_allclose(losses[True], losses[False], **TOL)


@pytest.mark.slow
def test_fleet_strategy_layer_scan_knob():
    """DistributedStrategy.layer_scan engages the pass at minimize time
    (segments default to loss._layer_checkpoints); composing with
    recompute rolls the scan with a remat body and drops the consumed
    interior boundaries from the recompute checkpoint list (the scan op
    then sits inside the prologue __segment__)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import bert

    def train(layer_scan, recompute=False):
        reset_programs(0)
        cfg = bert.BertConfig(vocab_size=256, hidden_size=16, num_layers=4,
                              num_heads=2, intermediate_size=32,
                              max_position=32, seq_len=8,
                              hidden_dropout=0.0, attention_dropout=0.0)
        ids, labels, loss = bert.build_pretrain_program(cfg)
        fleet.init(is_collective=True)
        s = fleet.DistributedStrategy()
        s.layer_scan = layer_scan
        if recompute:
            s.recompute = True
            s.recompute_configs = {
                "checkpoints": list(loss._layer_checkpoints)}
        opt = fleet.distributed_optimizer(
            paddle.optimizer.Adam(learning_rate=0.01), s)
        opt.minimize(loss)
        types = []
        for op in fluid.default_main_program().global_block().ops:
            types.append(op.type)
            types += [od["type"] for od in op.attrs.get("sub_ops", [])]
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(1)
        feed = {"input_ids": rng.randint(0, 256, (8, 8)).astype(np.int64),
                "mlm_labels": rng.randint(0, 256,
                                          (8, 8, 1)).astype(np.int64)}
        return ([np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                 for _ in range(2)], types)

    base, t_off = train(False)
    on, t_on = train(True)
    assert "__layer_scan__" not in t_off and "__layer_scan__" in t_on
    np.testing.assert_allclose(on, base, **TOL)
    rc, t_rc = train(True, recompute=True)
    assert "__layer_scan__" in t_rc and "__segment__" in t_rc
    np.testing.assert_allclose(rc, base, **TOL)


def test_non_isomorphic_segments_fall_back_unrolled():
    """A segment whose op sequence differs (third fc lacks the relu)
    leaves the program untouched — and still trainable — while an
    isomorphic fc stack rolls and matches its unrolled twin (the pass is
    model-agnostic)."""
    reset_programs(0)
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h1 = layers.fc(x, 8, act="relu")
    h2 = layers.fc(h1, 8, act="relu")
    h3 = layers.fc(h2, 8)                      # no act: not isomorphic
    loss = layers.mean(layers.square_error_cost(layers.fc(h3, 1), y))
    prog = fluid.default_main_program()
    n_before = len(prog.global_block().ops)
    assert apply_layer_scan(prog, [h1.name, h2.name, h3.name]) is None
    assert len(prog.global_block().ops) == n_before

    def train(rolled):
        reset_programs(3)
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h, bounds = x, []
        for _ in range(3):
            h = layers.fc(h, 8, act="relu")
            bounds.append(h.name)
        loss = layers.mean(layers.square_error_cost(layers.fc(h, 1), y))
        if rolled:
            assert apply_layer_scan(
                fluid.default_main_program(), bounds,
                startup_program=fluid.default_startup_program()) is not None
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(5)
        feed = {"x": rng.randn(16, 8).astype(np.float32),
                "y": rng.randn(16, 1).astype(np.float32)}
        return [float(np.asarray(exe.run(feed=feed,
                                         fetch_list=[loss])[0]))
                for _ in range(4)]

    np.testing.assert_allclose(train(True), train(False), **TOL)


@pytest.mark.slow
def test_stacked_param_checkpoints_roundtrip(tmp_path):
    """Stacked params flow through io.save_persistables/load_persistables
    as ordinary [L, ...] persistables, AND an UNROLLED checkpoint's
    per-layer entries load into a rolled program: the executor restacks
    them on the next run (loaded per-layer values win over the
    startup-stacked value) and drops the stale per-layer copies."""
    from paddle_tpu import io
    exe, feed, loss, _ = _build_bert(False)
    io.save_persistables(exe, str(tmp_path), fluid.default_main_program())
    l_ref = np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])

    exe, feed, loss, _ = _build_bert(True, seed=7)   # different init
    io.load_persistables(exe, str(tmp_path), fluid.default_main_program())
    l_rolled = np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
    np.testing.assert_allclose(l_rolled, l_ref, **TOL)
    assert global_scope().find("enc1_attn_qkv_w") is None, \
        "stale per-layer scope entries must be dropped after restacking"

    # rolled -> rolled round-trip of the stacked form
    d2 = str(tmp_path) + "_rolled"
    io.save_persistables(exe, d2, fluid.default_main_program())
    before = np.asarray(
        global_scope().find("enc0_attn_qkv_w@LAYERS")).copy()
    l_next = np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
    global_scope().set("enc0_attn_qkv_w@LAYERS", np.zeros_like(before))
    io.load_persistables(exe, d2, fluid.default_main_program())
    np.testing.assert_array_equal(
        np.asarray(global_scope().find("enc0_attn_qkv_w@LAYERS")), before)
    l_again = np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
    np.testing.assert_allclose(l_again, l_next, **TOL)
