"""Divergence sentinel + poison-batch rollback (resilience/integrity.py).

The dp-replication determinism contract makes every check exact: a
fingerprint mismatch IS corruption, and a rollback's resumed trajectory
must match the skip-oracle bit-for-bit.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.observability import metrics
from paddle_tpu.resilience import (DivergenceSentinel, ReplicaDivergenceError,
                                   RollbackExhausted, Snapshot,
                                   SnapshotManager, TrainingGuard,
                                   fingerprint)
from paddle_tpu.resilience.integrity import _split_quorum


def _build_net():
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, 8, act="tanh")
    p = layers.fc(h, 1)
    loss = layers.reduce_mean(layers.square_error_cost(p, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, fluid.default_main_program(), paddle.global_scope(), loss


def _feed(step, poison=False):
    x = np.random.RandomState(100 + step).randn(8, 4).astype(np.float32)
    y = np.random.RandomState(200 + step).randn(8, 1).astype(np.float32)
    if poison:
        x = x.copy()
        x[0, 0] = np.nan
    return {"x": x, "y": y}


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------

def test_fingerprint_exact_sensitivity():
    _, prog, scope, _ = _build_net()
    base = fingerprint(prog, scope)
    assert fingerprint(prog, scope) == base      # deterministic
    name = next(n for n in scope._vars if n.endswith("w_0"))
    orig = np.asarray(scope.find(name))
    flipped = orig.copy()
    flipped.flat[0] = np.nextafter(flipped.flat[0], np.inf)  # 1-ulp SDC
    scope.set(name, flipped)
    assert fingerprint(prog, scope) != base      # any bit flips the digest
    scope.set(name, orig)
    assert fingerprint(prog, scope) == base      # and it is pure


def test_split_quorum_majority_and_lowest_rank_tiebreak():
    assert _split_quorum({0: "a", 1: "a", 2: "b"}) == ("a", [2])
    assert _split_quorum({0: "a", 1: "b", 2: "b"}) == ("b", [0])
    # 1-vs-1 tie: the group holding the lowest rank is the quorum, so
    # every rank computes the SAME verdict (required for the heal round)
    assert _split_quorum({0: "a", 1: "b"}) == ("a", [1])
    assert _split_quorum({0: "a", 1: "a"}) == ("a", [])


# ---------------------------------------------------------------------------
# divergence sentinel (stub transport; real gloo in the chaos drill)
# ---------------------------------------------------------------------------

class _StubGloo:
    def __init__(self, rank, world, gathered, bcast=None):
        self.rank, self.world = rank, world
        self._gathered = gathered
        self._bcast = bcast

    def all_gather(self, value):
        return self._gathered(value)

    def broadcast(self, value, root=0):
        return self._bcast(value, root) if self._bcast else value


def test_sentinel_names_minority_rank_with_typed_error():
    _, prog, scope, _ = _build_net()
    metrics.reset()
    mine = fingerprint(prog, scope)
    gloo = _StubGloo(0, 3, lambda v: [(0, mine), (1, mine),
                                      (2, "0" * 64)])
    sent = DivergenceSentinel(gloo, interval=2, heal=False)
    assert sent.check(prog, scope, 3) is None    # off-cadence: no round
    with pytest.raises(ReplicaDivergenceError) as exc:
        sent.check(prog, scope, 4)
    assert exc.value.minority_ranks == [2]
    assert exc.value.step == 4
    assert "rank" in str(exc.value) and "[2]" in str(exc.value)
    assert metrics.get("integrity.fingerprint_mismatch") == 1


def test_sentinel_agreement_is_silent():
    _, prog, scope, _ = _build_net()
    mine = fingerprint(prog, scope)
    gloo = _StubGloo(1, 2, lambda v: [(0, mine), (1, mine)])
    sent = DivergenceSentinel(gloo, interval=1, heal=False)
    assert sent.check(prog, scope, 1) is None
    assert sent.last_minority == []


def test_quorum_heal_restores_every_rank_bit_identically(tmp_path):
    """On mismatch with a SnapshotManager, check() broadcasts the lowest
    quorum rank's newest snapshot and restores it locally, returning the
    replay-from step."""
    exe, prog, scope, loss = _build_net()
    metrics.reset()
    mgr = SnapshotManager(interval=2, root=str(tmp_path), rank=1, world=2)
    try:
        for s in range(1, 5):
            exe.run(prog, feed=_feed(s), fetch_list=[loss])
            mgr.maybe_capture(prog, scope, s, sync=True)
        clean = fingerprint(prog, scope)
        # corrupt THIS rank (rank 1): one ulp in one optimizer moment
        name = next(n for n in scope._vars if "moment" in n or
                    n.endswith("w_0"))
        bad = np.asarray(scope.find(name)).copy()
        bad.flat[0] = np.nextafter(bad.flat[0], np.inf)
        scope.set(name, bad)
        assert fingerprint(prog, scope) != clean

        # the quorum (rank 0) broadcasts its own snapshot — in a real gang
        # it is bit-identical to this rank's, so reuse mgr's payload
        def bcast(value, root):
            assert root == 0               # lowest quorum rank
            snap = mgr.latest()
            return (snap.step, {n: np.asarray(a)
                                for n, a in snap.arrays.items()})

        gloo = _StubGloo(1, 2,
                         lambda v: [(0, clean), (1, v[1])], bcast=bcast)
        sent = DivergenceSentinel(gloo, interval=2)
        healed = sent.check(prog, scope, 4, snapshots=mgr)
        assert healed == 4                 # newest snapshot step
        assert sent.last_minority == [1]
        assert metrics.get("integrity.quorum_restores") == 1
        # replaying from the healed snapshot reconverges bit-identically
        assert fingerprint(prog, scope) == clean
    finally:
        mgr.close()


def test_heal_without_quorum_snapshot_raises_original_error():
    _, prog, scope, _ = _build_net()
    gloo = _StubGloo(0, 2, lambda v: [(0, v[1]), (1, "f" * 64)],
                     bcast=lambda value, root: None)
    sent = DivergenceSentinel(gloo, interval=1)
    mgr = SnapshotManager(rank=0, world=2)   # empty: nothing to heal from
    try:
        with pytest.raises(ReplicaDivergenceError):
            sent.check(prog, scope, 1, snapshots=mgr)
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# TrainingGuard: poison-batch rollback
# ---------------------------------------------------------------------------

def test_nan_rollback_is_bit_identical_to_skipping_the_batch(tmp_path):
    poison = 5
    # run A: batch 5 NaN-poisoned; the guard rolls back + skips it
    exe, prog, scope, loss = _build_net()
    metrics.reset()
    mgr = SnapshotManager(interval=2, root=str(tmp_path), rank=0, world=1)
    guard = TrainingGuard(mgr, program=prog, scope=scope, budget=2)
    losses_a = {}
    try:
        for s in guard.steps(9, start=1):
            out, = exe.run(prog, feed=_feed(s, poison=(s == poison)),
                           fetch_list=[loss])
            lv = float(np.asarray(out).ravel()[0])
            if not guard.observe(s, lv):
                losses_a[s] = lv
                mgr.maybe_capture(prog, scope, s, sync=True)
        fp_a = fingerprint(prog, scope)
    finally:
        mgr.close()
    assert guard.rollbacks == 1 and guard.skip == {poison}
    assert metrics.get("integrity.rollbacks") == 1

    # run B: the oracle that never saw batch 5
    from paddle_tpu.framework import program as prog_mod
    from paddle_tpu.framework import scope as scope_mod
    from paddle_tpu.framework import unique_name
    prog_mod._main_program = prog_mod.Program()
    prog_mod._startup_program = prog_mod.Program()
    scope_mod._reset_global_scope()
    unique_name.switch()
    np.random.seed(0)
    exe, prog, scope, loss = _build_net()
    losses_b = {}
    for s in range(1, 9):
        if s == poison:
            continue
        out, = exe.run(prog, feed=_feed(s), fetch_list=[loss])
        losses_b[s] = float(np.asarray(out).ravel()[0])
    fp_b = fingerprint(prog, scope)

    post_a = {s: v for s, v in losses_a.items() if s > poison}
    post_b = {s: v for s, v in losses_b.items() if s > poison}
    assert post_a == post_b                # losses bit-identical after skip
    assert fp_a == fp_b                    # and so is the final state


def test_loss_spike_triggers_rollback():
    mgr = SnapshotManager(rank=0, world=1)
    guard = TrainingGuard(mgr, spike_factor=10.0, budget=1)
    try:
        snap_holder = Snapshot(2, {})
        mgr._buffers[0] = snap_holder
        mgr._newest = 0
        for s, lv in [(1, 1.0), (2, 0.9)]:
            assert not guard.observe(s, lv)
        assert guard.observe(3, 50.0)      # 50 > 10 x median(~0.95)
        assert guard.skip == {3}
    finally:
        mgr.close()


def test_rollback_budget_exhaustion_raises():
    mgr = SnapshotManager(rank=0, world=1)
    mgr._buffers[0] = Snapshot(1, {})
    mgr._newest = 0
    guard = TrainingGuard(mgr, budget=0)
    try:
        with pytest.raises(RollbackExhausted):
            guard.observe(2, float("nan"))
    finally:
        mgr.close()


def test_rollback_without_snapshot_raises():
    mgr = SnapshotManager(rank=0, world=1)   # never captured
    guard = TrainingGuard(mgr, budget=3)
    try:
        with pytest.raises(RollbackExhausted):
            guard.observe(2, float("inf"))
    finally:
        mgr.close()


def test_steps_generator_rewinds_and_skips():
    mgr = SnapshotManager(rank=0, world=1)
    guard = TrainingGuard(mgr, budget=3)
    mgr._buffers[0] = Snapshot(2, {})
    mgr._newest = 0
    visited = []
    try:
        for s in guard.steps(7, start=1):
            visited.append(s)
            if s == 4 and 4 not in guard.skip:
                guard.observe(4, float("nan"))
            else:
                guard.observe(s, 1.0)
    finally:
        mgr.close()
    # 1,2,3,4 then rollback-to-2 -> replay 3, skip 4, continue 5,6
    assert visited == [1, 2, 3, 4, 3, 5, 6]
