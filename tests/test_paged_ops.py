"""Paged KV-cache ops (ops/paged_ops.py): the block-pool write/gather/
attend primitives under both consumers — pure-jax (what the serving
engine traces) and the registered static-graph ops (what the analysis
layer verifies and the Executor can run). The load-bearing property is
BIT-parity with the dense ring-cache formulation: gathered block content
must equal a dense cache holding the same positions, and masked (stale /
scratch) positions must contribute exactly-zero attention weight."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.analysis import op_specs  # noqa: F401  (installs OpSpecs)
from paddle_tpu.ops import paged_ops
from paddle_tpu.ops import registry
from paddle_tpu.testing import reset_programs

L, NB, NH, BS, HD = 2, 16, 2, 4, 8
MB = 3          # max blocks per slot -> max_len 12
B = 3


def _pools():
    import jax.numpy as jnp
    return (jnp.zeros((L, NB, NH, BS, HD), jnp.float32),
            jnp.zeros((L, NB, NH, BS, HD), jnp.float32))


def _page_table():
    # slot 0 -> blocks 1,2,3; slot 1 -> 4,5,6; slot 2 -> 7,8,9
    return np.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9]], np.int32)


def test_update_then_gather_is_dense():
    """Writing positions 0..n-1 through paged_update and gathering back
    reconstructs exactly the dense [nh, max_len, hd] cache."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    kp, vp = _pools()
    pt = jnp.asarray(_page_table())
    n_pos = MB * BS
    dense = np.zeros((B, NH, n_pos, HD), np.float32)
    for pos in range(n_pos):
        k1 = rng.randn(B, NH, HD).astype(np.float32)
        v1 = rng.randn(B, NH, HD).astype(np.float32)
        kp, vp = paged_ops.paged_update(
            kp, vp, jnp.asarray(k1), jnp.asarray(v1), pt,
            jnp.full((B,), pos, jnp.int32), BS, layer=1)
        dense[:, :, pos] = k1
    got = np.asarray(paged_ops.paged_gather(kp, pt, layer=1))
    np.testing.assert_array_equal(got, dense)
    # layer 0 untouched
    assert not np.asarray(paged_ops.paged_gather(kp, pt, layer=0)).any()


def test_inactive_rows_write_scratch_only():
    import jax.numpy as jnp
    kp, vp = _pools()
    pt = jnp.asarray(_page_table())
    k1 = np.ones((B, NH, HD), np.float32)
    active = jnp.asarray([True, False, True])
    kp, vp = paged_ops.paged_update(
        kp, vp, jnp.asarray(k1), jnp.asarray(k1), pt,
        jnp.zeros((B,), jnp.int32), BS, layer=0, active=active)
    kp_np = np.asarray(kp)
    assert kp_np[0, 1].any() and kp_np[0, 7].any()   # active slots' blocks
    assert not kp_np[0, 4].any()                     # frozen slot untouched
    assert kp_np[0, paged_ops.SCRATCH_BLOCK].any()   # redirected write


def test_paged_attend_matches_dense_attend():
    """paged_attend == gpt_decode._attend over the dense equivalent cache,
    bitwise — including when stale garbage sits in masked positions."""
    import jax.numpy as jnp
    from paddle_tpu.models.gpt_decode import _attend
    rng = np.random.RandomState(1)
    kp, vp = _pools()
    # poison the WHOLE pool: only written positions may matter
    kp = kp + jnp.asarray(rng.randn(*kp.shape).astype(np.float32))
    vp = vp + jnp.asarray(rng.randn(*vp.shape).astype(np.float32))
    pt = jnp.asarray(_page_table())
    pos = jnp.asarray([2, 5, 0], jnp.int32)   # per-slot lengths differ
    n_pos = MB * BS
    for p in range(int(pos.max()) + 1):
        k1 = rng.randn(B, NH, HD).astype(np.float32)
        v1 = rng.randn(B, NH, HD).astype(np.float32)
        kp, vp = paged_ops.paged_update(
            kp, vp, jnp.asarray(k1), jnp.asarray(v1), pt,
            jnp.full((B,), p, jnp.int32), BS, layer=0)
    q = jnp.asarray(rng.randn(B, NH, 1, HD).astype(np.float32))
    got = paged_ops.paged_attend(q, kp, vp, pt, pos, BS, layer=0)

    k_dense = paged_ops.paged_gather(kp, pt, layer=0)
    v_dense = paged_ops.paged_gather(vp, pt, layer=0)
    mask = jnp.where(jnp.arange(n_pos)[None, :] <= pos[:, None],
                     0.0, -jnp.inf).astype(jnp.float32)[:, None, None, :]
    want = _attend(q, k_dense, v_dense, mask, 1.0 / np.sqrt(HD))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_registered_ops_have_specs_and_rules():
    """ISSUE-14 satellite: the decode/paged ops carry OpSpec registry
    metadata (slots + sharding rule), so program_lint --assert-coverage
    sees no debt when the serving program joins the zoo."""
    for op in ("paged_attention", "paged_cache_update", "linear_chain_crf",
               "crf_decoding", "gather_tree", "beam_search",
               "beam_search_decode"):
        assert registry.has(op), op
        spec = registry.get_spec(op)
        assert spec is not None, f"{op} has no OpSpec"
        assert registry.get_sharding_rule(op), f"{op} has no sharding rule"
        from paddle_tpu.analysis.sharding import RULES
        assert registry.get_sharding_rule(op) in RULES


def test_verifier_catches_malformed_paged_op():
    """The OpSpec is enforced: a paged_attention desc missing its required
    block_size attr (or carrying an unknown slot) is a build-time verifier
    finding, not a trace-time crash."""
    from paddle_tpu.analysis import verify_program
    reset_programs(seed=0)
    gb = fluid.default_main_program().global_block()
    for nm, shape in (("q", (B, NH * HD)), ("pt", (B, MB)), ("pos", (B,))):
        gb.create_var(name=nm, shape=shape, dtype="float32", is_data=True)
    gb.create_parameter(name="kp", shape=(L, NB, NH, BS, HD),
                        dtype="float32", trainable=False)
    gb.create_parameter(name="vp", shape=(L, NB, NH, BS, HD),
                        dtype="float32", trainable=False)
    gb.create_var(name="ctx", shape=(B, NH * HD), dtype="float32")
    from paddle_tpu.framework.program import Operator
    op = Operator(gb, "paged_attention",
                  {"Q": ["q"], "KPool": ["kp"], "VPool": ["vp"],
                   "PageTable": ["pt"], "Pos": ["pos"]},
                  {"Out": ["ctx"]}, {})          # block_size MISSING
    gb.ops.append(op)
    findings = verify_program(fluid.default_main_program(),
                              feed_names=["q", "pt", "pos"],
                              fetch_names=["ctx"])
    assert any(f.check == "missing_attr" and "block_size" in f.message
               for f in findings), [f.to_dict() for f in findings]


def test_serving_program_executes_and_matches_pure_ops():
    """The static twin is not just lintable — the Executor runs it, and
    its output equals the pure paged_attend math the engine traces."""
    import jax.numpy as jnp
    from paddle_tpu.serving.program import build_decode_step_program
    reset_programs(seed=0)
    build_decode_step_program()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    nslots, h, mb, bs = 4, 16, 4, 8
    feed = {
        "dec_q": rng.randn(nslots, h).astype(np.float32),
        "dec_k_new": rng.randn(nslots, h).astype(np.float32),
        "dec_v_new": rng.randn(nslots, h).astype(np.float32),
        "dec_page_table": np.asarray(
            [[1, 2, 0, 0], [3, 4, 0, 0], [5, 6, 0, 0], [7, 8, 0, 0]],
            np.int32),
        "dec_pos": np.asarray([0, 3, 7, 2], np.int32),
    }
    out, = exe.run(feed=feed, fetch_list=["dec_context"])
    kp = jnp.zeros((2, 64, 2, 8, 8), jnp.float32)
    vp = jnp.zeros_like(kp)
    kp, vp = paged_ops.paged_update(
        kp, vp, feed["dec_k_new"].reshape(nslots, 2, 8),
        feed["dec_v_new"].reshape(nslots, 2, 8),
        jnp.asarray(feed["dec_page_table"]),
        jnp.asarray(feed["dec_pos"]), bs, 0)
    ctx = paged_ops.paged_attend(
        feed["dec_q"].reshape(nslots, 2, 1, 8), kp, vp,
        jnp.asarray(feed["dec_page_table"]),
        jnp.asarray(feed["dec_pos"]), bs)
    ref = np.asarray(ctx.transpose(0, 2, 1, 3).reshape(nslots, h))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)
