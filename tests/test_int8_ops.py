"""INT8 dequantize tail + fused LSTM ops (reference tests:
test_dequantize_abs_max_op.py, test_dequantize_log_op.py,
test_lookup_table_dequant_op.py, test_fake_quantize_op.py,
test_attention_lstm_op.py, test_fused_emb_fc_lstm_op.py)."""
import numpy as np

import paddle_tpu  # noqa: F401
from op_test import run_op

R = np.random.RandomState(0)


def test_dequantize_abs_max():
    x = R.randint(-127, 128, (4, 6)).astype(np.int8)
    scale = np.array([3.5], np.float32)
    out = run_op("dequantize_abs_max",
                 {"X": [x], "Scale": [scale]}, {"max_range": 127.0})
    np.testing.assert_allclose(np.asarray(out["Out"][0]),
                               x.astype(np.float32) * 3.5 / 127.0,
                               rtol=1e-6)


def test_dequantize_log_sign_folding():
    dic = np.linspace(0.1, 12.8, 128).astype(np.float32)
    x = np.array([[-128, -1, 0, 5, 127]], np.int8)
    out = np.asarray(run_op("dequantize_log",
                            {"X": [x], "Dict": [dic]}, {})["Out"][0])
    expect = np.array([[-dic[0], -dic[127], dic[0], dic[5], dic[127]]])
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_lookup_table_dequant():
    v, width = 5, 6
    mn = R.uniform(-2, -1, (v, 1)).astype(np.float32)
    mx = R.uniform(1, 2, (v, 1)).astype(np.float32)
    payload = R.randint(0, 256, (v, width)).astype(np.float32)
    w = np.concatenate([mn, mx, payload], axis=1)
    ids = np.array([[0], [3], [4]], np.int64)
    out = np.asarray(run_op("lookup_table_dequant",
                            {"W": [w], "Ids": [ids]},
                            {"quant_bits": 8})["Out"][0])
    for r, i in enumerate([0, 3, 4]):
        scale = (mx[i, 0] - mn[i, 0]) / 256.0
        np.testing.assert_allclose(out[r], scale * payload[i] + mn[i, 0],
                                   rtol=1e-5)


def test_fake_quantize_moving_average_abs_max():
    x = R.randn(8, 8).astype(np.float32) * 2
    state = np.array([1.0], np.float32)
    accum = np.array([1.5], np.float32)
    out = run_op("fake_quantize_moving_average_abs_max",
                 {"X": [x], "InState": [state], "InAccum": [accum]},
                 {"bit_length": 8, "moving_rate": 0.9})
    new_state = 0.9 * 1.0 + 1.0
    new_accum = 0.9 * 1.5 + np.abs(x).max()
    scale = new_accum / new_state
    np.testing.assert_allclose(
        float(np.asarray(out["OutScale"][0]).reshape(-1)[0]), scale,
        rtol=1e-5)
    q = np.asarray(out["Out"][0])
    np.testing.assert_allclose(
        q, np.clip(np.round(x / scale * 127), -127, 127), atol=1e-4)


def _np_attention_lstm(x, lens, c0, h0, attn_w, lstm_w, lstm_b):
    """Loop oracle mirroring attention_lstm_op.cc:333-434."""
    b, t, m = x.shape
    d = c0.shape[-1]
    wh, wx = lstm_w[:d], lstm_w[d:]
    sig = lambda v: 1 / (1 + np.exp(-v))
    hidden = np.zeros((b, t, d), np.float32)
    cell = np.zeros((b, t, d), np.float32)
    for bi in range(b):
        h, c = h0[bi].copy(), c0[bi].copy()
        for tt in range(lens[bi]):
            seq = x[bi, :lens[bi]]
            cat = np.concatenate(
                [seq, np.tile(c[None, :], (lens[bi], 1))], -1)
            fc = np.maximum(cat @ attn_w[:, 0], 0.0)
            e = np.exp(fc - fc.max())
            probs = e / e.sum()
            lx = probs @ seq
            gates = lx @ wx + h @ wh + lstm_b
            f, i, o = sig(gates[:d]), sig(gates[d:2 * d]), \
                sig(gates[2 * d:3 * d])
            cand = np.tanh(gates[3 * d:])
            c = f * c + i * cand
            h = o * np.tanh(c)
            hidden[bi, tt], cell[bi, tt] = h, c
    return hidden, cell


def test_attention_lstm_matches_loop_oracle():
    b, t, m, d = 2, 5, 3, 4
    x = R.randn(b, t, m).astype(np.float32) * 0.5
    lens = np.array([5, 3], np.int64)
    c0 = R.randn(b, d).astype(np.float32) * 0.3
    h0 = R.randn(b, d).astype(np.float32) * 0.3
    attn_w = R.randn(m + d, 1).astype(np.float32)
    lstm_w = R.randn(d + m, 4 * d).astype(np.float32) * 0.4
    lstm_b = R.randn(1, 4 * d).astype(np.float32) * 0.1
    out = run_op("attention_lstm",
                 {"X": [x], "SeqLen": [lens], "C0": [c0], "H0": [h0],
                  "AttentionWeight": [attn_w], "LSTMWeight": [lstm_w],
                  "LSTMBias": [lstm_b]}, {})
    want_h, want_c = _np_attention_lstm(x, lens, c0, h0, attn_w, lstm_w,
                                        lstm_b.reshape(-1))
    np.testing.assert_allclose(np.asarray(out["Hidden"][0]), want_h,
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(out["Cell"][0]), want_c,
                               rtol=2e-5, atol=2e-6)


def test_fused_embedding_fc_lstm_matches_lstm():
    """ids -> premultiplied table rows == feeding those rows to the lstm op
    directly (fused_embedding_fc_lstm_op.cc's contract)."""
    v, b, t, d = 7, 2, 4, 3
    table = R.randn(v, 4 * d).astype(np.float32) * 0.3
    ids = R.randint(0, v, (b, t, 1)).astype(np.int64)
    wh = R.randn(d, 4 * d).astype(np.float32) * 0.3
    bias = R.randn(4 * d).astype(np.float32) * 0.1
    lens = np.array([4, 2], np.int64)
    fused = run_op("fused_embedding_fc_lstm",
                   {"Ids": [ids], "Embeddings": [table], "WeightH": [wh],
                    "Bias": [bias], "SeqLen": [lens]}, {})
    proj = table[ids[..., 0]]
    plain = run_op("lstm", {"Input": [proj], "Weight": [wh],
                            "Bias": [bias], "SeqLen": [lens]}, {})
    np.testing.assert_allclose(np.asarray(fused["Hidden"][0]),
                               np.asarray(plain["Hidden"][0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fused["Cell"][0]),
                               np.asarray(plain["Cell"][0]), rtol=1e-6)


def test_depthwise_conv2d_transpose():
    c = 3
    x = R.randn(2, c, 5, 5).astype(np.float32)
    w = R.randn(c, 1, 3, 3).astype(np.float32)
    out = run_op("depthwise_conv2d_transpose",
                 {"Input": [x], "Filter": [w]},
                 {"strides": [1, 1], "paddings": [0, 0],
                  "dilations": [1, 1]})
    got = np.asarray(out["Output"][0])
    assert got.shape == (2, c, 7, 7)
    # depthwise independence: zeroing channel 1's input zeroes ONLY its out
    x2 = x.copy()
    x2[:, 1] = 0
    got2 = np.asarray(run_op("depthwise_conv2d_transpose",
                             {"Input": [x2], "Filter": [w]},
                             {"strides": [1, 1], "paddings": [0, 0],
                              "dilations": [1, 1]})["Output"][0])
    np.testing.assert_allclose(got2[:, 1], 0, atol=1e-6)
    np.testing.assert_allclose(got2[:, 0], got[:, 0], rtol=1e-5)
    np.testing.assert_allclose(got2[:, 2], got[:, 2], rtol=1e-5)


def test_conv2d_transpose_matches_scatter_oracle():
    """Base-op value check (conv2d_transpose_op.cc semantics): scatter-add
    formulation out[co, i*s+ki-p, j*s+kj-p] += x[ci,i,j] * w[ci,co,ki,kj].
    Round 4 fixed the kernel-layout declaration (C_in != C_out crashed
    before) and the stride-1 padding mapping."""
    n, ci, co, h, k, s, p = 2, 2, 3, 4, 3, 2, 1
    x = R.randn(n, ci, h, h).astype(np.float32)
    w = R.randn(ci, co, k, k).astype(np.float32)
    out = np.asarray(run_op("conv2d_transpose",
                            {"Input": [x], "Filter": [w]},
                            {"strides": [s, s], "paddings": [p, p],
                             "dilations": [1, 1]})["Output"][0])
    ho = (h - 1) * s - 2 * p + k
    assert out.shape == (n, co, ho, ho)
    want = np.zeros((n, co, ho + 2 * p, ho + 2 * p), np.float32)
    for bi in range(n):
        for c_in in range(ci):
            for c_out in range(co):
                for i in range(h):
                    for j in range(h):
                        want[bi, c_out, i * s:i * s + k, j * s:j * s + k] \
                            += x[bi, c_in, i, j] * w[c_in, c_out]
    want = want[:, :, p:p + ho, p:p + ho]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_fake_quantize_moving_average_is_test_uses_calibrated_scale():
    """clone(for_test=True) programs must quantize with the trained
    calibration, not batch stats (fake_quantize_op.cc test branch)."""
    x = R.randn(4, 4).astype(np.float32) * 10
    in_scale = np.array([2.0], np.float32)
    out = run_op("fake_quantize_moving_average_abs_max",
                 {"X": [x], "InScale": [in_scale],
                  "InState": [np.array([1.0], np.float32)],
                  "InAccum": [np.array([1.0], np.float32)]},
                 {"bit_length": 8, "is_test": True})
    np.testing.assert_allclose(
        np.asarray(out["OutScale"][0]).reshape(-1), [2.0])
    assert "OutState" not in out  # moving average untouched in eval
    np.testing.assert_allclose(np.asarray(out["Out"][0]),
                               np.clip(np.round(x / 2.0 * 127), -127, 127))


def test_attention_lstm_grads_flow():
    """attention_lstm is on the training path (unlike the reference's
    inference-only fusion): grads must flow to x and both weight sets."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import registry

    b, t, m, d = 2, 4, 3, 4
    x = R.randn(b, t, m).astype(np.float32) * 0.5
    c0 = np.zeros((b, d), np.float32)
    attn_w = R.randn(m + d, 1).astype(np.float32)
    lstm_w = R.randn(d + m, 4 * d).astype(np.float32) * 0.4
    lstm_b = np.zeros((1, 4 * d), np.float32)
    opdef = registry.get("attention_lstm")

    def loss(xv, aw, lw):
        out = opdef.lower(
            registry.LowerCtx(rng_key=jax.random.PRNGKey(0)),
            {"X": [xv], "C0": [jnp.asarray(c0)],
             "AttentionWeight": [aw], "LSTMWeight": [lw],
             "LSTMBias": [jnp.asarray(lstm_b)]}, {})
        return jnp.sum(out["Hidden"][0] ** 2)

    gx, gaw, glw = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(attn_w), jnp.asarray(lstm_w))
    for g in (gx, gaw, glw):
        arr = np.asarray(g)
        assert np.isfinite(arr).all() and np.abs(arr).max() > 0
