"""Custom-op surface (reference: fluid.load_op_library framework.py:5549,
framework/c/c_api.h; reference test: test_custom_op.py building
librelu2_op_from_op so via setup.py)."""
import os
import subprocess
import tempfile
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.framework import errors
from paddle_tpu.utils import register_op, custom_layer, load_op_library


@pytest.fixture(scope="module")
def scaled_tanh_registered():
    from paddle_tpu.ops import registry
    if not registry.has("test_scaled_tanh"):
        import jax.numpy as jnp

        @register_op("test_scaled_tanh")
        def test_scaled_tanh(x, scale=1.0):
            return jnp.tanh(x) * scale
    return "test_scaled_tanh"


def test_python_custom_op_forward(scaled_tanh_registered):
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = custom_layer("test_scaled_tanh")(x, scale=2.0)
    exe = fluid.Executor()
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out, = exe.run(feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, np.tanh(xv) * 2.0, rtol=1e-6)


def test_python_custom_op_is_differentiable(scaled_tanh_registered):
    # the headline feature vs the reference: no grad kernel required
    x = layers.data(name="x", shape=[4], dtype="float32")
    x.stop_gradient = False
    y = custom_layer("test_scaled_tanh")(x, scale=3.0)
    loss = layers.mean(y)
    grads = fluid.gradients([loss], [x])
    exe = fluid.Executor()
    xv = np.random.RandomState(1).randn(2, 4).astype(np.float32)
    g, = exe.run(feed={"x": xv}, fetch_list=[grads[0]])
    expect = 3.0 * (1 - np.tanh(xv) ** 2) / xv.size
    np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)


def test_collision_rejected(scaled_tanh_registered):
    with pytest.raises(errors.AlreadyExistsError):
        register_op("relu")(lambda x: x)
    with pytest.raises(errors.AlreadyExistsError):
        register_op(scaled_tanh_registered)(lambda x: x)


def test_load_py_library(tmp_path):
    lib = tmp_path / "my_ops.py"
    lib.write_text(textwrap.dedent("""
        import jax.numpy as jnp
        from paddle_tpu.utils import register_op

        @register_op("test_softsign_from_lib")
        def softsign(x):
            return x / (1 + jnp.abs(x))
    """))
    added = load_op_library(str(lib))
    assert "test_softsign_from_lib" in added
    x = layers.data(name="x", shape=[3], dtype="float32")
    y = custom_layer("test_softsign_from_lib")(x)
    exe = fluid.Executor()
    xv = np.array([[-2.0, 0.0, 2.0]], np.float32)
    out, = exe.run(feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv / (1 + np.abs(xv)), rtol=1e-6)


C_SRC = r"""
#include "custom_op.h"
#include <math.h>

static int32_t relu_infer(const PD_CTensor* ins, int32_t n_ins,
                          PD_CTensor* outs, int32_t n_outs) {
  outs[0] = ins[0];
  return 0;
}

static int32_t relu_compute(const PD_CTensor* ins, int32_t n_ins,
                            PD_CTensor* outs, int32_t n_outs) {
  long long n = 1;
  for (int i = 0; i < ins[0].ndim; ++i) n *= ins[0].dims[i];
  const float* src = (const float*)ins[0].data;
  float* dst = (float*)outs[0].data;
  for (long long i = 0; i < n; ++i) dst[i] = src[i] > 0 ? src[i] : 0.f;
  return 0;
}

/* second op: row sums, proves non-trivial infer_shape */
static int32_t rowsum_infer(const PD_CTensor* ins, int32_t n_ins,
                            PD_CTensor* outs, int32_t n_outs) {
  if (ins[0].ndim != 2) return 1;
  outs[0].ndim = 1;
  outs[0].dims[0] = ins[0].dims[0];
  outs[0].dtype = ins[0].dtype;
  return 0;
}

static int32_t rowsum_compute(const PD_CTensor* ins, int32_t n_ins,
                              PD_CTensor* outs, int32_t n_outs) {
  long long r = ins[0].dims[0], c = ins[0].dims[1];
  const float* src = (const float*)ins[0].data;
  float* dst = (float*)outs[0].data;
  for (long long i = 0; i < r; ++i) {
    float s = 0.f;
    for (long long j = 0; j < c; ++j) s += src[i * c + j];
    dst[i] = s;
  }
  return 0;
}

static const PD_CustomOpDef kOps[] = {
    {"test_c_relu", 1, 1, relu_infer, relu_compute},
    {"test_c_rowsum", 1, 1, rowsum_infer, rowsum_compute},
};

int32_t PD_GetCustomOps(const PD_CustomOpDef** defs) {
  *defs = kOps;
  return 2;
}
"""


@pytest.fixture(scope="module")
def c_oplib():
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    d = tempfile.mkdtemp(prefix="pd_custom_op_")
    src = os.path.join(d, "my_ops.cc")
    with open(src, "w") as f:
        f.write(C_SRC)
    so = os.path.join(d, "my_ops.so")
    hdr = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "native")
    subprocess.run(["g++", "-shared", "-fPIC", "-O2", f"-I{hdr}", src,
                    "-o", so], check=True)
    return so


def test_c_custom_ops(c_oplib):
    added = load_op_library(c_oplib)
    assert set(added) == {"test_c_relu", "test_c_rowsum"}
    assert load_op_library(c_oplib) == added  # idempotent

    x = layers.data(name="x", shape=[5], dtype="float32")
    r = custom_layer("test_c_relu")(x)
    s = custom_layer("test_c_rowsum")(r)
    exe = fluid.Executor()
    xv = np.random.RandomState(2).randn(4, 5).astype(np.float32)
    rv, sv = exe.run(feed={"x": xv}, fetch_list=[r, s])
    np.testing.assert_allclose(rv, np.maximum(xv, 0), rtol=1e-6)
    np.testing.assert_allclose(sv, np.maximum(xv, 0).sum(1), rtol=1e-5)
    # declared shape from the C infer_shape: rank-1 with the dynamic batch
    assert tuple(s.shape) == (-1,)


def test_so_without_symbol_rejected(tmp_path):
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    src = tmp_path / "empty.cc"
    src.write_text("extern \"C\" int nothing() { return 0; }\n")
    so = tmp_path / "empty.so"
    subprocess.run(["g++", "-shared", "-fPIC", str(src), "-o", str(so)],
                   check=True)
    from paddle_tpu.utils import CustomOpError
    with pytest.raises(CustomOpError, match="PD_GetCustomOps"):
        load_op_library(str(so))
