"""AST dygraph→static conversion: data-dependent `if`/`while` become
__cond__/__while__ ops and match eager execution on both branch outcomes
(reference dygraph_to_static parity tests)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.dy2static import convert_to_static


def model_if(x):
    s = layers.reduce_sum(x)
    if s > 0:
        y = x * 2.0
        tag = s + 100.0
    else:
        y = x - 1.0
        tag = s - 100.0
    return y + 0.0 * tag, tag


def model_while(x):
    total = layers.reshape(layers.reduce_sum(x), [1])
    steps = layers.fill_constant([1], "float32", 0.0)
    while total > 1.0:
        total = total * 0.5
        steps = steps + 1.0
    return total, steps


def _run_static(fn, x_np):
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    outs = convert_to_static(fn)(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in
            exe.run(feed={"x": x_np}, fetch_list=list(outs))]


def _run_eager(fn, x_np):
    paddle.disable_static()
    try:
        outs = fn(paddle.to_tensor(x_np))
        return [np.asarray(o.numpy()) for o in outs]
    finally:
        paddle.enable_static()


def test_if_converts_to_cond_op_and_matches_eager():
    pos = np.ones((2, 4), np.float32)
    neg = -np.ones((2, 4), np.float32)
    # static program built ONCE must handle BOTH branch outcomes at runtime
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    y, tag = convert_to_static(model_if)(x)
    ops = [op.type for op in fluid.default_main_program().global_block().ops]
    assert "__cond__" in ops, ops
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for x_np in (pos, neg):
        got_y, got_tag = exe.run(feed={"x": x_np}, fetch_list=[y, tag])
        want_y, want_tag = _run_eager(model_if, x_np)
        np.testing.assert_allclose(np.asarray(got_y), want_y, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got_tag), want_tag, rtol=1e-5)


def test_while_converts_to_while_op_and_matches_eager():
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    total, steps = convert_to_static(model_while)(x)
    ops = [op.type for op in fluid.default_main_program().global_block().ops]
    assert "__while__" in ops, ops
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for scale in (8.0, 0.25):   # data-dependent iteration counts (incl. 0)
        x_np = np.full((1, 4), scale, np.float32)
        got = exe.run(feed={"x": x_np}, fetch_list=[total, steps])
        want = _run_eager(model_while, x_np)
        np.testing.assert_allclose(np.asarray(got[0]).reshape(-1),
                                   np.asarray(want[0]).reshape(-1),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got[1]).reshape(-1),
                                   np.asarray(want[1]).reshape(-1),
                                   rtol=1e-5)


def test_logical_ops_and_python_fallback():
    def f(x, flag):
        s = layers.reduce_sum(x)
        if flag and x.shape[-1] > 0:          # plain python condition
            z = x + 1.0
        else:
            z = x - 1.0
        return (z,)

    x_np = np.ones((2, 4), np.float32)
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    (z,) = convert_to_static(f)(x, True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out, = exe.run(feed={"x": x_np}, fetch_list=[z])
    np.testing.assert_allclose(np.asarray(out), x_np + 1.0)


def test_tensor_logical_and_in_condition():
    def f(x):
        s = layers.reduce_sum(x)
        if (s > 0.0) and (s < 10.0):
            y = x * 3.0
        else:
            y = x * 0.0
        return (y,)

    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    (y,) = convert_to_static(f)(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    inside = np.full((1, 4), 1.0, np.float32)     # sum=4 in (0,10)
    outside = np.full((1, 4), 5.0, np.float32)    # sum=20 not < 10
    o1, = exe.run(feed={"x": inside}, fetch_list=[y])
    o2, = exe.run(feed={"x": outside}, fetch_list=[y])
    np.testing.assert_allclose(np.asarray(o1), inside * 3.0)
    np.testing.assert_allclose(np.asarray(o2), outside * 0.0)


def test_loop_temporaries_and_guard_returns():
    """Review regressions: per-iteration temporaries must not become loop
    carries, and assignment-free early-return guards stay pure python."""
    def f_tmp(n):
        y = 0
        while y < n:
            t = 1
            y = y + t
        return y

    assert convert_to_static(f_tmp)(3) == 3

    def f_guard(x):
        if x is None:
            return 0
        return x + 1

    g = convert_to_static(f_guard)
    assert g(None) == 0 and g(4) == 5


def test_python_value_in_tensor_branch():
    """Plain-python assignments inside a tensor branch are promoted to
    Variables (reference to_static_variable)."""
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)

    def f(x):
        s = layers.reduce_sum(x)
        if s > 0:
            y = x * 2.0
            flag = 1.0
        else:
            y = x - 1.0
            flag = 0.0
        return y, flag

    x = layers.data(name="x", shape=[4], dtype="float32")
    y, flag = convert_to_static(f)(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    ov, fv = exe.run(feed={"x": np.ones((1, 4), np.float32)},
                     fetch_list=[y, flag])
    assert float(np.asarray(fv).reshape(-1)[0]) == 1.0
    ov, fv = exe.run(feed={"x": -np.ones((1, 4), np.float32)},
                     fetch_list=[y, flag])
    assert float(np.asarray(fv).reshape(-1)[0]) == 0.0


# --- regression tests: review findings r2 ---------------------------------

def test_loop_temporary_read_after_loop():
    """A body temporary consumed after the loop is loop-carried."""
    def f(n):
        i = 0
        t = 0
        while i < n:
            t = i * 10
            i = i + 1
        return t

    assert convert_to_static(f)(3) == 20
    assert convert_to_static(f)(0) == 0


def test_branch_read_modify_write():
    """s = s + 1 inside a converted branch (was UnboundLocalError)."""
    def f(x):
        s = x
        if s > 0:
            s = s + 1
        return s

    assert convert_to_static(f)(2) == 3
    assert convert_to_static(f)(-2) == -2


def test_nested_control_flow_converts():
    """if-in-if and if-in-while must not trip the return detector."""
    def f(a, b):
        out = 0
        if a > 0:
            if b > 0:
                out = 1
            else:
                out = 2
        else:
            out = 3
        return out

    g = convert_to_static(f)
    assert (g(1, 1), g(1, -1), g(-1, 1)) == (1, 2, 3)

    def h(n):
        i = 0
        acc = 0
        while i < n:
            if i % 2 == 0:
                acc = acc + i
            i = i + 1
        return acc

    assert convert_to_static(h)(5) == 6


def test_single_branch_assignment_no_nameerror():
    """A name assigned in only one branch must not break the other path."""
    def f(x):
        if x > 0:
            y = 1
        else:
            z = 2
        return x

    assert convert_to_static(f)(5) == 5
    assert convert_to_static(f)(-5) == -5


def test_real_return_still_rejected():
    import pytest

    def f(x):
        s = x
        if s > 0:
            s = s - 1
            return s
        return s

    with pytest.raises(NotImplementedError):
        convert_to_static(f)


def test_static_nested_if_in_while_parity():
    """Nested tensor control flow lowers and matches eager."""
    def body(x):
        total = layers.reshape(layers.reduce_sum(x), [1])
        steps = layers.fill_constant([1], "float32", 0.0)
        while total > 1.0:
            if steps < 2.0:
                total = total * 0.25
            else:
                total = total * 0.5
            steps = steps + 1.0
        return total, steps

    x_np = np.full((2, 4), 4.0, np.float32)   # sum=32 → 8 → 2 → 1 → stop
    static = _run_static(body, x_np)
    eager = _run_eager(body, x_np)
    for s, e in zip(static, eager):
        np.testing.assert_allclose(s, e, rtol=1e-6)


def test_one_sided_unread_assignment_allowed():
    """A name assigned in only one branch and never read afterwards must not
    flow UNDEF into the cond merge (the reference's UndefinedVar only errors
    on a real read). The read result `y` is two-sided and carried."""
    def f(x):
        s = layers.reduce_sum(x)
        if s > 0:
            scratch = s + 1.0      # one-sided, never read again
            y = x * 2.0
        else:
            y = x - 1.0
        return (y,)

    for fill in (2.0, -2.0):
        x_np = np.full((2, 4), fill, np.float32)
        static = _run_static(f, x_np)
        eager = _run_eager(f, x_np)
        np.testing.assert_allclose(static[0], eager[0], rtol=1e-6)
