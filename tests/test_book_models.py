"""End-to-end "book" model tests (reference fluid/tests/book/): full
build -> train -> save -> infer loops on tiny synthetic datasets.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def _synthetic_digits(n, seed=0):
    """Tiny separable 'digit' problem: class = argmax of 10 fixed projections."""
    rng = np.random.RandomState(seed)
    proj = rng.rand(784, 10).astype(np.float32)
    x = rng.rand(n, 1, 28, 28).astype(np.float32)
    y = (x.reshape(n, -1) @ proj).argmax(1).astype(np.int64)[:, None]
    return x, y


def _lenet(img, label):
    conv1 = fluid.layers.conv2d(img, num_filters=6, filter_size=5, padding=2,
                                act="relu")
    pool1 = fluid.layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = fluid.layers.conv2d(pool1, num_filters=16, filter_size=5,
                                act="relu")
    pool2 = fluid.layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = fluid.layers.fc(pool2, size=120, act="relu")
    fc2 = fluid.layers.fc(fc1, size=84, act="relu")
    logits = fluid.layers.fc(fc2, size=10)
    loss = fluid.layers.softmax_with_cross_entropy(logits, label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return logits, avg_loss, acc


def test_recognize_digits_lenet_train_save_infer(tmp_path):
    paddle.seed(7)
    img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    logits, avg_loss, acc = _lenet(img, label)
    opt = paddle.optimizer.Adam(learning_rate=2e-3)
    opt.minimize(avg_loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    x, y = _synthetic_digits(256)
    bs = 32
    losses, accs = [], []
    for epoch in range(8):
        for i in range(0, len(x), bs):
            lv, av = exe.run(feed={"img": x[i:i + bs], "label": y[i:i + bs]},
                             fetch_list=[avg_loss, acc])
            losses.append(float(lv))
            accs.append(float(av))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    assert np.mean(accs[-8:]) > np.mean(accs[:8]), "accuracy should improve"

    # save inference model, reload, check parity with direct logits
    fluid.io.save_inference_model(str(tmp_path), ["img"], [logits], exe)
    direct, = exe.run(fluid.default_main_program().clone(for_test=True),
                      feed={"img": x[:8], "label": y[:8]},
                      fetch_list=[logits])

    infer_prog, feed_names, fetch_vars = fluid.io.load_inference_model(
        str(tmp_path), exe)
    assert feed_names == ["img"]
    loaded, = exe.run(infer_prog, feed={"img": x[:8]}, fetch_list=fetch_vars)
    np.testing.assert_allclose(direct, loaded, rtol=1e-4, atol=1e-5)


def test_fit_a_line():
    """Reference book/test_fit_a_line.py: linear regression converges."""
    paddle.seed(3)
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    true_w = rng.rand(13, 1).astype(np.float32)
    xv = rng.rand(64, 13).astype(np.float32)
    yv = xv @ true_w + 0.1

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = last = None
    for _ in range(100):
        lv, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < 0.05 * first


def test_word2vec_embeddings():
    """Reference book/test_word2vec.py: embedding + fc skip-gram-ish model."""
    paddle.seed(11)
    vocab, emb_dim = 50, 16
    w_in = fluid.layers.data(name="w_in", shape=[1], dtype="int64")
    w_out = fluid.layers.data(name="w_out", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(w_in, size=[vocab, emb_dim])
    emb = fluid.layers.reshape(emb, [-1, emb_dim])
    logits = fluid.layers.fc(emb, size=vocab)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, w_out))
    paddle.optimizer.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(0)
    pairs_in = rng.randint(0, vocab, (128, 1)).astype(np.int64)
    pairs_out = (pairs_in + 1) % vocab  # deterministic "context"

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = last = None
    for _ in range(60):
        lv, = exe.run(feed={"w_in": pairs_in, "w_out": pairs_out},
                      fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < 0.5 * first


def test_machine_translation_seq2seq_beam_decode():
    """Book test #4 (reference test_machine_translation.py): train a tiny
    GRU seq2seq on a copy task, then decode with beam search — the beam-1
    hypothesis must reproduce the source, and beam scores must be ordered."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.disable_static()
    try:
        V, H, T = 12, 64, 4
        start, end = 1, 0

        class Seq2Seq(nn.Layer):
            def __init__(self):
                super().__init__()
                self.src_emb = nn.Embedding(V, H)
                self.tgt_emb = nn.Embedding(V, H)
                self.enc = nn.GRUCell(H, H)
                self.dec = nn.GRUCell(H, H)
                self.out = nn.Linear(H, V)

            def encode(self, src):
                b = src.shape[0]
                h = paddle.zeros([b, H], dtype="float32")
                for t in range(src.shape[1]):
                    h, _ = self.enc(self.src_emb(src[:, t]), h)
                return h

            def decode_step(self, tok, h):
                h2, _ = self.dec(self.tgt_emb(tok), h)
                return self.out(h2), h2

        import numpy as np
        rng = np.random.RandomState(0)
        net = Seq2Seq()
        opt = paddle.optimizer.Adam(learning_rate=0.02,
                                    parameters=net.parameters())
        src_np = rng.randint(2, V, (8, T)).astype(np.int64)
        # teacher-forced training on the copy task: target == source + end
        # (140 steps at lr .02 memorizes the 8 fixed sequences; eager-mode
        # op dispatch makes each step expensive on CPU — suite hygiene)
        for step in range(140):
            src = paddle.to_tensor(src_np)
            h = net.encode(src)
            tok = paddle.to_tensor(np.full((8,), start, np.int64))
            loss = 0
            for t in range(T + 1):
                logits, h = net.decode_step(tok, h)
                tgt = (src_np[:, t] if t < T
                       else np.full((8,), end)).astype(np.int64)
                loss = loss + F.cross_entropy(
                    logits, paddle.to_tensor(tgt.reshape(-1, 1)))
                tok = paddle.to_tensor(tgt)
            loss.backward()
            opt.step()
            opt.clear_grad()

        # beam decode must reproduce the memorized mapping
        h0 = net.encode(paddle.to_tensor(src_np[:4]))
        from paddle_tpu import layers
        dec = layers.BeamSearchDecoder(
            lambda tok, st: net.decode_step(tok, st),
            start_token=start, end_token=end, beam_size=3)
        ids, scores = layers.dynamic_decode(dec, inits=h0,
                                            max_step_num=T + 1,
                                            batch_size=4)
        assert ids.shape[:2] == (4, 3)
        best = ids[:, 0, :T]
        acc = (best == src_np[:4]).mean()
        assert acc > 0.9, (best, src_np[:4])
        # scores sorted best-first
        assert (np.diff(scores, axis=1) <= 1e-5).all()
    finally:
        paddle.enable_static()
