"""Fault-tolerant serving (paddle_tpu/serving/resilience.py): the
ISSUE-15 acceptance pins.

* replica FAILOVER is bit-lossless: a FaultPlan-killed engine's in-flight
  requests re-dispatch to a healthy replica and finish bit-identical to
  an undisturbed oracle run (decode is a pure function of
  (prompt, seed, token_idx)); the failover budget turns repeat victims
  into a typed RequestFailedError;
* ADMISSION CONTROL sheds typed: queue_full / deadline_unmeetable /
  unfundable / draining / admit_fault, each counted under
  serving.shed_total + serving.shed.<reason> and raised as ShedError;
* graceful DRAIN finishes in-flight work and hands back the unstarted
  queue;
* RESURRECTION rebuilds a dead engine's cache against the shared weights
  and re-admits it only past the canary gate
  (live -> suspect -> dead -> resurrecting -> live);
* replicas hold ONE weight copy (prepare_params never runs for a clone).
"""
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.flags import flag, set_flags
from paddle_tpu.models.gpt import GPTConfig, build_lm_program
from paddle_tpu.models import gpt_decode
from paddle_tpu.resilience import clear_plan, install_plan
from paddle_tpu.serving import (DecodeEngine, Health, NoHealthyReplicaError,
                                Request, RequestFailedError,
                                RoundRobinFrontend, ServingFrontend,
                                ShedError, replicated_engines)
from paddle_tpu.serving import engine as engine_mod
from paddle_tpu.serving.request import RequestState
from paddle_tpu.testing import reset_programs

# Tier-1 rebalance (ISSUE 16): ~41s; the failover/shed/resurrection pins
# here are re-proven end-to-end by ci.py's serving chaos drill
# (scripts/chaos_smoke.py --serving-drill) on every CI pass.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_gpt():
    reset_programs(seed=0)
    cfg = GPTConfig.tiny()
    cfg.max_position = 64
    build_lm_program(cfg)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return cfg, gpt_decode.params_from_scope(cfg)


@pytest.fixture(autouse=True)
def _fast_health_ticks():
    set_flags({"FLAGS_serving_health_interval_ms": 30.0})
    yield
    clear_plan()
    set_flags({"FLAGS_serving_health_interval_ms": 200.0})


GEO = dict(max_slots=3, block_size=8, num_blocks=32, max_len=32, window=4)


def _engine(cfg, params, **kw):
    base = dict(GEO)
    base.update(kw)
    return DecodeEngine(params, cfg, **base)


def _mixed_requests(cfg, n=6, seed=3):
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        sampled = i % 2 == 1            # greedy AND seeded top-k
        reqs.append(Request(
            prompt=rng.randint(0, cfg.vocab_size,
                               (int(rng.randint(3, 12)),)),
            max_new_tokens=int(rng.randint(4, 9)),
            temperature=0.8 if sampled else 0.0,
            top_k=16 if sampled else 0,
            seed=100 + i, uid=f"r{i}"))
    return reqs


def _oracle(cfg, params, reqs):
    clear_plan()
    eng = _engine(cfg, params)
    try:
        comps = eng.generate(reqs, timeout=240)
    finally:
        eng.stop()
    assert all(c.ok for c in comps), [(c.uid, c.state) for c in comps]
    return {c.uid: c.tokens for c in comps}


def _wait(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# ---------------------------------------------------------------------------
# one-weight-copy invariant (satellite: clone double-prepare fix)
# ---------------------------------------------------------------------------

def test_clone_prepares_once_and_shares_device_buffers(tiny_gpt,
                                                       monkeypatch):
    cfg, params = tiny_gpt
    calls = []
    real = engine_mod.prepare_params

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(engine_mod, "prepare_params", counting)
    engines = replicated_engines(3, params, cfg, **GEO)
    try:
        # prepare_params ran ONCE for the whole replica set...
        assert len(calls) == 1
        src = engines[0]
        for clone in engines[1:]:
            # ...and every clone holds the SAME device buffers (identity,
            # not equality: one weight copy in HBM)
            assert clone.params is src.params
            for k in src.params:
                assert clone.params[k] is src.params[k]
            assert clone.scales is src.scales
            assert clone.compute_dtype == src.compute_dtype
    finally:
        for e in engines:
            e.stop()


# ---------------------------------------------------------------------------
# failover: bit-parity + budget
# ---------------------------------------------------------------------------

def test_failover_bit_parity_vs_oracle(tiny_gpt):
    """The acceptance pin: a replica killed mid-decode (FaultPlan window
    fault) loses nothing — every request completes bit-identical to the
    undisturbed single-engine oracle, greedy and seeded top-k alike."""
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    reqs = _mixed_requests(cfg, n=6)
    want = _oracle(cfg, params, reqs)
    for name in ("serving.failovers", "serving.engine_failures",
                 "serving.shed_total"):
        m.reset(name)
    plan = install_plan("serving.window:error:at=2", seed=0)
    engines = replicated_engines(2, params, cfg, **GEO)
    fe = ServingFrontend(engines, resurrect=False)
    try:
        handles = []
        for r in reqs:
            handles.append(fe.submit(r))
            time.sleep(0.002)       # staggered: both replicas get load
        comps = [h.result(timeout=240, raise_on_error=False)
                 for h in handles]
    finally:
        clear_plan()
        fe.stop()
    assert all(c.ok for c in comps), \
        [(c.uid, c.state, c.error) for c in comps if not c.ok]
    for c in comps:
        assert c.tokens == want[c.uid], (c.uid, c.tokens, want[c.uid])
    assert sum(r.fired for r in plan.rules) == 1
    assert m.get("serving.engine_failures") == 1
    assert m.get("serving.failovers") == len(fe.failover_log) >= 1
    assert m.get("serving.shed_total") == 0


def test_window_fault_single_victim_counts_one_failover(tiny_gpt):
    """FaultPlan-driven window fault with exactly one in-flight request
    -> exactly one failover counted, tokens still oracle-identical."""
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    req = Request(prompt=np.arange(2, 8) % cfg.vocab_size,
                  max_new_tokens=12, uid="solo")
    want = _oracle(cfg, params, [req])
    m.reset("serving.failovers")
    install_plan("serving.window:error:at=2", seed=0)
    engines = replicated_engines(2, params, cfg, **GEO, )
    fe = ServingFrontend(engines, resurrect=False)
    try:
        c = fe.submit(req).result(timeout=240)
    finally:
        clear_plan()
        fe.stop()
    assert c.tokens == want["solo"]
    assert m.get("serving.failovers") == 1
    assert fe.failover_log == ["solo"]


def test_failover_budget_exhausted_raises_typed(tiny_gpt):
    """Every window faults on every replica: the request burns its
    failover budget and fails with the typed RequestFailedError; with
    resurrection off the frontend then has no healthy replica."""
    cfg, params = tiny_gpt
    set_flags({"FLAGS_serving_failover_budget": 1})
    install_plan("serving.window:error:every=1", seed=0)
    engines = replicated_engines(2, params, cfg, **GEO)
    fe = ServingFrontend(engines, resurrect=False)
    try:
        h = fe.submit(Request(prompt=np.arange(4) % cfg.vocab_size,
                              max_new_tokens=6, uid="doomed"))
        with pytest.raises(RequestFailedError) as ei:
            h.result(timeout=60)
        assert ei.value.completion.finish_reason in (
            "failover budget exhausted", "no healthy replica for failover")
        assert _wait(lambda: all(e._dead is not None for e in engines),
                     timeout=10)
        with pytest.raises(NoHealthyReplicaError):
            fe.submit(Request(prompt=np.arange(4) % cfg.vocab_size,
                              max_new_tokens=2))
    finally:
        clear_plan()
        set_flags({"FLAGS_serving_failover_budget": 2})
        fe.stop()


# ---------------------------------------------------------------------------
# admission control + load shedding
# ---------------------------------------------------------------------------

def test_shed_reason_taxonomy(tiny_gpt, monkeypatch):
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    for name in ("serving.shed_total", "serving.shed.queue_full",
                 "serving.shed.deadline_unmeetable",
                 "serving.shed.unfundable", "serving.shed.draining",
                 "serving.shed.admit_fault"):
        m.reset(name)

    def mk(plen=4, new=4, **kw):
        return Request(prompt=np.arange(1, 1 + plen) % cfg.vocab_size,
                       max_new_tokens=new, **kw)

    # service thread disabled so the queue only grows
    eng = _engine(cfg, params, max_queue=3)
    monkeypatch.setattr(eng, "_ensure_thread", lambda: None)
    try:
        # admit_fault: the FaultPlan admission site sheds typed
        install_plan("serving.admit:error:at=1", seed=0)
        h = eng.submit(mk())
        clear_plan()
        with pytest.raises(ShedError) as ei:
            h.result(timeout=5)
        assert ei.value.reason == "admit_fault"

        assert eng.submit(mk()).state == RequestState.QUEUED
        assert eng.submit(mk()).state == RequestState.QUEUED

        # deadline_unmeetable: with a measured window EWMA and two queued
        # requests, a millisecond deadline cannot be met
        eng._window_ms_ewma = 1000.0
        assert eng.queue_wait_estimate_ms() > 0
        h = eng.submit(mk(new=4, deadline_ms=0.5))
        with pytest.raises(ShedError) as ei:
            h.result(timeout=5)
        assert ei.value.reason == "deadline_unmeetable"

        # queue_full: the submit-queue bound sheds past max_queue
        assert eng.submit(mk()).state == RequestState.QUEUED
        h = eng.submit(mk())
        with pytest.raises(ShedError) as ei:
            h.result(timeout=5)
        assert ei.value.reason == "queue_full"

        # draining: drained engines shed new work and hand back the queue
        unstarted = eng.drain(timeout_s=5)
        assert len(unstarted) == 3
        h = eng.submit(mk())
        with pytest.raises(ShedError) as ei:
            h.result(timeout=5)
        assert ei.value.reason in ("draining", "engine_dead")
        assert ei.value.reason == "draining" or eng._dead is None
    finally:
        eng.stop()

    # unfundable: a budget the pool could NEVER fund sheds at submit
    small = _engine(cfg, params, num_blocks=3, max_len=32)
    try:
        h = small.submit(mk(plen=9, new=10))
        with pytest.raises(ShedError) as ei:
            h.result(timeout=5)
        assert ei.value.reason == "unfundable"
    finally:
        small.stop()

    # 1 admit_fault + 1 deadline + 1 queue_full + 1 unfundable + 4
    # draining (3 handed-back by drain + 1 post-drain submit)
    assert m.get("serving.shed_total") == 8.0
    for reason in ("queue_full", "deadline_unmeetable", "admit_fault",
                   "unfundable"):
        assert m.get(f"serving.shed.{reason}") == 1.0, reason
    assert m.get("serving.shed.draining") == 4.0


def test_queue_wait_histogram_observed(tiny_gpt):
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    m.reset("serving.queue_wait_ms")
    eng = _engine(cfg, params)
    try:
        comps = eng.generate(_mixed_requests(cfg, n=3, seed=9),
                             timeout=240)
    finally:
        eng.stop()
    assert all(c.ok for c in comps)
    snap = m.snapshot()["serving.queue_wait_ms"]
    assert snap["count"] == 3 and snap["p50"] is not None


def test_least_loaded_routing(tiny_gpt, monkeypatch):
    """Submissions land on the replica with the fewest pending decode
    tokens, not blindly round-robin."""
    cfg, params = tiny_gpt
    engines = replicated_engines(2, params, cfg, **GEO)
    for e in engines:
        monkeypatch.setattr(e, "_ensure_thread", lambda: None)
    fe = ServingFrontend(engines, resurrect=False)
    try:
        def mk(new, uid):
            return Request(prompt=np.arange(4) % cfg.vocab_size,
                           max_new_tokens=new, uid=uid)
        fe.submit(mk(8, "big"))            # engine A: load 8
        for i in range(4):
            fe.submit(mk(1, f"s{i}"))      # all land on B (loads 1..4)
        fe.submit(mk(1, "s4"))             # B at 4 < A at 8 -> B again
        loads = sorted(e.load() for e in engines)
        queues = sorted(len(e._queue) for e in engines)
        assert loads == [5, 8]
        assert queues == [1, 5]
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

def test_drain_finishes_inflight_and_hands_back_unstarted(tiny_gpt):
    cfg, params = tiny_gpt
    eng = _engine(cfg, params, max_slots=1, window=2)
    try:
        a = eng.submit(Request(prompt=np.arange(5) % cfg.vocab_size,
                               max_new_tokens=10, uid="inflight"))
        assert _wait(lambda: a.state == RequestState.DECODE, timeout=60)
        b = eng.submit(Request(prompt=np.arange(5) % cfg.vocab_size,
                               max_new_tokens=4, uid="unstarted"))
        unstarted = eng.drain(timeout_s=60)
        # the in-flight request DECODED TO COMPLETION...
        ca = a.result(timeout=60)
        assert ca.ok and len(ca.tokens) == 10
        # ...the unstarted one came back typed, with its Request intact
        assert [r.uid for r, _ in unstarted] == ["unstarted"]
        with pytest.raises(ShedError) as ei:
            b.result(timeout=5)
        assert ei.value.reason == "draining"
    finally:
        eng.stop()


def test_frontend_drain_returns_requests_and_sheds_new(tiny_gpt,
                                                       monkeypatch):
    cfg, params = tiny_gpt
    engines = replicated_engines(2, params, cfg, **GEO)
    for e in engines:
        monkeypatch.setattr(e, "_ensure_thread", lambda: None)
    fe = ServingFrontend(engines, resurrect=False)
    try:
        reqs = _mixed_requests(cfg, n=4, seed=5)
        handles = [fe.submit(r) for r in reqs]
        handed_back = fe.drain(timeout_s=10)
        assert sorted(r.uid for r in handed_back) == \
            sorted(r.uid for r in reqs)
        for h in handles:
            with pytest.raises(ShedError):
                h.result(timeout=5)
        # post-drain submits shed without touching any engine
        c = fe.submit(reqs[0]).result(timeout=5, raise_on_error=False)
        assert c.finish_reason == "shed:draining"
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# resurrection + canary gate
# ---------------------------------------------------------------------------

def test_resurrection_canary_gate(tiny_gpt):
    """A dead replica rebuilds its pool, passes the canary bit-compare
    against a live replica, and rejoins: live -> suspect -> dead ->
    resurrecting -> live. Then it serves again."""
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    engines = replicated_engines(2, params, cfg, **GEO)
    fe = ServingFrontend(engines)
    try:
        # warm both replicas (compile) before the kill
        comps = fe.generate(_mixed_requests(cfg, n=4, seed=7),
                            timeout=240)
        assert all(c.ok for c in comps)
        victim = engines[1]
        m.reset("serving.resurrections")
        victim.kill("induced death")
        # the kill defers to the service thread's window boundary: wait
        # for death to land, THEN for the health loop to resurrect
        assert _wait(lambda: victim.health != Health.LIVE, timeout=30)
        assert _wait(lambda: victim.health == Health.LIVE
                     and victim._dead is None, timeout=60), \
            (victim.health, victim._dead, victim.health_history)
        assert victim.health_history == [
            Health.LIVE, Health.SUSPECT, Health.DEAD,
            Health.RESURRECTING, Health.LIVE]
        assert m.get("serving.resurrections") >= 1
        assert fe.stats()["live"] == 2
        # the resurrected replica serves real traffic again
        req = Request(prompt=np.arange(3, 9) % cfg.vocab_size,
                      max_new_tokens=5, uid="post")
        c = victim.submit(req).result(timeout=240)
        assert c.ok and len(c.tokens) == 5
    finally:
        fe.stop()


def test_resurrection_canary_mismatch_keeps_engine_dead(tiny_gpt):
    """The gate is real: a replica whose canary does NOT match the
    expectation never rejoins; the budget exhausts typed and counted."""
    from paddle_tpu.observability import metrics as m
    cfg, params = tiny_gpt
    set_flags({"FLAGS_serving_resurrect_budget": 2})
    engines = replicated_engines(2, params, cfg, **GEO)
    fe = ServingFrontend(engines)
    try:
        m.reset("serving.resurrect_gave_up")
        fe._canary_tokens = [-1, -1, -1]     # unsatisfiable expectation
        victim = engines[1]
        victim.kill("induced death")
        assert _wait(lambda: id(victim) in fe._gave_up, timeout=60)
        assert victim.health == Health.DEAD
        assert "canary" in (victim._dead or "") \
            or "resurrection budget" in (victim._dead or "")
        assert m.get("serving.resurrect_gave_up") == 1
        assert fe.stats()["live"] == 1       # survivor still serves
        c = fe.submit(Request(prompt=np.arange(4) % cfg.vocab_size,
                              max_new_tokens=3)).result(timeout=240)
        assert c.ok
    finally:
        set_flags({"FLAGS_serving_resurrect_budget": 3})
        fe.stop()


# ---------------------------------------------------------------------------
# SLA trip -> failover (the PR-14 fail-hard path, now recoverable)
# ---------------------------------------------------------------------------

def test_sla_trip_fails_over_instead_of_failing_requests(tiny_gpt):
    """PR 14's brittle contract inverted: behind the resilient frontend,
    an SLA-tripped window re-dispatches its in-flight requests instead of
    killing them."""
    cfg, params = tiny_gpt
    engines = replicated_engines(2, params, cfg, **GEO)
    fe = ServingFrontend(engines, resurrect=False)
    # warm both, then wedge ONLY replica 0's window dispatch
    comps = fe.generate(_mixed_requests(cfg, n=4, seed=11), timeout=240)
    assert all(c.ok for c in comps)
    victim = engines[0]
    real = victim._window_jit

    def wedged(*a, **kw):
        time.sleep(30)
        return real(*a, **kw)

    victim._window_jit = wedged
    set_flags({"FLAGS_step_deadline_ms": 300.0})
    try:
        req = Request(prompt=np.arange(6) % cfg.vocab_size,
                      max_new_tokens=6, uid="sla")
        h = victim.submit(req)          # force it onto the wedged replica
        c = h.result(timeout=120)       # raises if it FAILED
        assert c.ok and len(c.tokens) == 6
        assert h.failovers >= 1
        assert victim._dead is not None
    finally:
        set_flags({"FLAGS_step_deadline_ms": 0.0})
        fe.stop()


# ---------------------------------------------------------------------------
# bench row shape (degraded-capacity arm)
# ---------------------------------------------------------------------------

def test_bench_degraded_row_shape():
    import bench
    row = bench.bench_serving_degraded(
        streams=4, dtype="float32", prompt_len=8, new_tokens=4,
        model="tiny", replicas=2)
    assert row["metric"] == "serving_degraded_tokens_per_sec"
    assert row["serving_degraded_arm"] is True
    assert row["replicas"] == 2 and row["replicas_killed"] == 1
    assert row["value"] > 0
    assert row.get("failed_requests", 0) == 0
    assert "ttft_p99_ms" in row and "failovers" in row
