"""Data pipeline: native data plane, fluid.dataset, DataLoader, DataFeeder.

Mirrors reference tests test_dataset.py, test_dataloader_*.py,
test_multiprocess_dataloader_*.py.
"""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.native.dataplane import NativeDataPlane, SlotSpec


@pytest.fixture(autouse=True)
def fresh_programs():
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)
    yield


def _write_multislot(tmp_path, n_files=2, rows=10):
    """Each row: dense float slot dim 2 (value i, i/2) + id slot dim 3."""
    paths = []
    for f in range(n_files):
        p = tmp_path / f"part-{f}"
        with open(p, "w") as fh:
            for i in range(rows):
                v = f * rows + i
                fh.write(f"2 {v} {v / 2} 3 {v} {v + 1} {v + 2}\n")
        paths.append(str(p))
    return paths


def test_native_dataplane_streaming_and_memory(tmp_path):
    paths = _write_multislot(tmp_path)
    dp = NativeDataPlane([SlotSpec("x", "float", 2),
                          SlotSpec("ids", "int64", 3)],
                         batch_size=4, n_threads=2)
    assert dp._h is not None, "native dataplane must compile (g++ available)"
    dp.set_files(paths)

    seen = []
    for b in dp:
        assert b["x"].dtype == np.float32 and b["ids"].dtype == np.int64
        seen.extend(b["x"][:, 0].tolist())
    assert sorted(seen) == [float(v) for v in range(20)]

    dp.load_into_memory()
    assert dp.memory_size() == 20
    dp.local_shuffle(seed=7)
    shuffled = [v for b in dp for v in b["x"][:, 0].tolist()]
    assert sorted(shuffled) == [float(v) for v in range(20)]
    assert shuffled != [float(v) for v in range(20)]  # actually shuffled
    dp.release_memory()
    assert dp.memory_size() == 0


def test_fluid_dataset_train_from_dataset(tmp_path):
    paths = _write_multislot(tmp_path, n_files=2, rows=16)
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
    emb = layers.embedding(ids, size=[64, 4])
    feat = layers.concat([layers.reduce_sum(emb, dim=1), x], axis=1)
    pred = layers.fc(feat, size=1)
    loss = layers.reduce_mean(layers.square(pred))
    paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)

    ds = fluid.dataset.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(8)
    ds.set_thread(2)
    ds.set_use_var([x, ids])
    ds.set_filelist(paths)
    ds.load_into_memory()
    ds.local_shuffle()
    assert ds.get_memory_data_size() == 32

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = exe.train_from_dataset(fluid.default_main_program(), ds,
                                 fetch_list=[loss])
    assert out is not None and np.isfinite(out[0]).all()


class _SquaresDataset(paddle.io.Dataset):
    def __init__(self, n=23):
        self.n = n

    def __getitem__(self, i):
        return np.float32([i]), np.float32([i * i])

    def __len__(self):
        return self.n


def test_dataloader_single_process_order_and_len():
    ds = _SquaresDataset(23)
    dl = paddle.io.DataLoader(ds, batch_size=5, shuffle=False,
                              drop_last=False)
    assert len(dl) == 5
    xs = [b[0] for b in dl]
    flat = np.concatenate([np.asarray(x).ravel() for x in xs])
    np.testing.assert_allclose(flat, np.arange(23, dtype=np.float32))


def test_dataloader_multiprocess_matches_single():
    ds = _SquaresDataset(31)
    dl0 = paddle.io.DataLoader(ds, batch_size=4, shuffle=False,
                               num_workers=0, use_buffer_reader=False)
    dl2 = paddle.io.DataLoader(ds, batch_size=4, shuffle=False,
                               num_workers=2, use_buffer_reader=False)
    a = np.concatenate([np.asarray(b[0]).ravel() for b in dl0])
    b = np.concatenate([np.asarray(bb[0]).ravel() for bb in dl2])
    np.testing.assert_allclose(a, b)  # order preserved across workers


class _BadDataset(paddle.io.Dataset):
    """Module-level: multiprocess workers (forkserver) pickle the dataset."""

    def __getitem__(self, i):
        if i == 3:
            raise ValueError("boom-at-3")
        return np.float32([i])

    def __len__(self):
        return 8


def test_dataloader_worker_error_surfaces():
    dl = paddle.io.DataLoader(_BadDataset(), batch_size=2, num_workers=2,
                              use_buffer_reader=False)
    with pytest.raises(RuntimeError, match="worker"):
        list(dl)


def test_dataloader_shuffle_reshuffles_between_epochs():
    ds = _SquaresDataset(32)
    dl = paddle.io.DataLoader(ds, batch_size=4, shuffle=True,
                              use_buffer_reader=False)
    e1 = np.concatenate([np.asarray(b[0]).ravel() for b in dl])
    e2 = np.concatenate([np.asarray(b[0]).ravel() for b in dl])
    assert sorted(e1.tolist()) == sorted(e2.tolist())
    assert not np.array_equal(e1, e2)


def test_from_generator_feeds_training():
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)

    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)

    def batch_gen():
        rng = np.random.RandomState(0)
        for _ in range(20):
            xb = rng.randn(16, 3).astype(np.float32)
            yield xb, xb @ w_true

    loader = fluid.io.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_batch_generator(batch_gen)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = []
    for feed in loader:
        feed = {k: np.asarray(v) for k, v in feed.items()}
        lv, = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5


def test_tensor_dataset_random_split_and_feeder():
    xs = np.arange(20, dtype=np.float32).reshape(10, 2)
    ys = np.arange(10, dtype=np.int64)
    ds = paddle.io.TensorDataset([xs, ys])
    a, b = paddle.io.random_split(ds, [7, 3], generator=0)
    assert len(a) == 7 and len(b) == 3

    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="int64")
    feeder = fluid.DataFeeder(feed_list=[x, y])
    feed = feeder.feed([ds[i] for i in range(4)])
    assert feed["x"].shape == (4, 2)
    assert feed["y"].shape == (4, 1)
    assert feed["y"].dtype == np.int64


def test_distributed_batch_sampler_shards():
    ds = _SquaresDataset(20)
    s0 = paddle.io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                           rank=0)
    s1 = paddle.io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                           rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == len(i1) == 10
    assert sorted(i0 + i1) == list(range(20))


def test_distributed_batch_sampler_len_is_per_rank():
    ds = _SquaresDataset(1000 // 10)  # 100 samples
    s = paddle.io.DistributedBatchSampler(ds, batch_size=10, num_replicas=4,
                                          rank=0)
    assert len(s) == len(list(s)) == 3  # ceil(100/4)=25 -> 3 batches of 10


def test_train_from_dataset_overlaps_parse_with_compute():
    """The data plane must hide batch parse time behind device steps
    (reference trainer.h:51 Trainer/DeviceWorker purpose): with parse and
    compute each ~30ms, overlapped wall time stays well under the serial
    sum. Also checks correctness: prefetch order preserved and final loss
    identical to a serial loop."""
    import time
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)

    x = fluid.layers.data(name="px", shape=[256, 256], dtype="float32")
    h = x
    for _ in range(6):   # enough matmuls to give the device real work
        h = fluid.layers.matmul(h, h)
        h = fluid.layers.scale(h, 1e-3)
    out = fluid.layers.reduce_mean(h)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    n_batches, parse_s = 10, 0.03
    rng = np.random.RandomState(0)
    batches = [rng.randn(4, 256, 256).astype(np.float32) * 0.01
               for _ in range(n_batches)]

    class SlowDataset:
        def __iter__(self):
            for b in batches:
                time.sleep(parse_s)      # simulated MultiSlot parse
                yield {"px": b}

    # warm the compile cache so timing measures steady-state
    exe.run(feed={"px": batches[0]}, fetch_list=[out])

    # bounded retry on the TIMING comparison only (correctness asserts stay
    # single-shot): on the shared CPU backend a GC pause or scheduler blip
    # can eat the 15% margin in any one sample — a real overlap regression
    # fails every attempt (the jax-cpu-timing-tests rule: timing A/Bs need
    # real per-step compute + bounded retry or they flake)
    for attempt in range(3):
        t0 = time.perf_counter()
        last = exe.train_from_dataset(fluid.default_main_program(),
                                      SlowDataset(), fetch_list=[out])
        overlapped = time.perf_counter() - t0

        t0 = time.perf_counter()
        for b in batches:
            time.sleep(parse_s)
            serial_last = exe.run(feed={"px": b}, fetch_list=[out])
        serial = time.perf_counter() - t0

        np.testing.assert_allclose(np.asarray(last[0]),
                                   np.asarray(serial_last[0]), rtol=1e-6)
        if overlapped < serial * 0.85:
            break
    else:
        # parse alone is 0.3s; overlapped must beat serial clearly
        raise AssertionError(
            f"no overlap in 3 attempts: last overlapped={overlapped:.3f}s "
            f"serial={serial:.3f}s")


def test_train_from_dataset_fast_producer_slow_consumer_terminates():
    """Producer finishing while the bounded queue is full must not lose the
    end sentinel (regression: put_nowait(_END) raised Full -> consumer
    blocked on q.get() forever)."""
    import time
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = fluid.layers.data(name="px", shape=[2], dtype="float32")
    out = fluid.layers.reduce_mean(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    class FastDataset:          # produces instantly; consumer compiles/steps
        def __iter__(self):     # slower, so the queue (maxsize 4) fills
            for i in range(12):
                yield {"px": np.full((1, 2), float(i), np.float32)}

    done = []

    def _run():
        done.append(exe.train_from_dataset(fluid.default_main_program(),
                                           FastDataset(), fetch_list=[out]))

    import threading
    t = threading.Thread(target=_run, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "train_from_dataset deadlocked (lost sentinel)"
    np.testing.assert_allclose(np.asarray(done[0][0]), 11.0)


def test_train_from_dataset_producer_error_propagates():
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = fluid.layers.data(name="px", shape=[2], dtype="float32")
    out = fluid.layers.reduce_mean(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    class BadDataset:
        def __iter__(self):
            yield {"px": np.zeros((1, 2), np.float32)}
            raise RuntimeError("corrupt record at byte 42")

    with pytest.raises(RuntimeError, match="corrupt record"):
        exe.train_from_dataset(fluid.default_main_program(), BadDataset(),
                               fetch_list=[out])


def test_train_from_dataset_failed_step_does_not_leak_producer():
    """A step failure mid-epoch must unblock + join the prefetch thread
    (no orphan holding the dataset open)."""
    import threading
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = fluid.layers.data(name="px", shape=[2], dtype="float32")
    h = fluid.layers.fc(x, 3)          # pins px's trailing dim to 2
    out = fluid.layers.reduce_mean(h)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    class EndlessDataset:
        def __iter__(self):
            yield {"px": np.zeros((1, 2), np.float32)}    # fine
            while True:
                # wrong trailing dim -> the matmul fails to trace
                yield {"px": np.zeros((1, 5), np.float32)}

    before = threading.active_count()
    with pytest.raises(Exception):
        exe.train_from_dataset(fluid.default_main_program(),
                               EndlessDataset(), fetch_list=[out])
    # producer must have exited (generator finalized via GeneratorExit or
    # stop flag); give the join a moment
    for t in threading.enumerate():
        assert not (t.name == "dataplane-prefetch" and t.is_alive()), \
            "prefetch thread leaked"
    assert threading.active_count() <= before + 1


class _SlowAtZeroDataset(paddle.io.Dataset):
    """Index 0 stalls long enough for the test to SIGKILL its worker."""

    def __getitem__(self, i):
        if i == 0:
            time.sleep(120)
        return np.float32([i])

    def __len__(self):
        return 8


def test_dataloader_fast_worker_death_detection():
    """A SIGKILLed worker must surface within the ~1s liveness poll, not the
    300s queue timeout — the forkserver-context equivalent of the reference's
    SIGCHLD handler (dataloader_iter.py _set_SIGCHLD_handler: 'DataLoader
    worker exits unexpectedly')."""
    from paddle_tpu.dataloader.dataloader import (_MultiprocessIter,
                                                  default_collate_fn)
    batches = [[i, i + 1] for i in range(0, 8, 2)]
    it = _MultiprocessIter(_SlowAtZeroDataset(), batches,
                           default_collate_fn, num_workers=2)
    # worker 0 owns batch seq 0 (round-robin) and is stuck in sleep(120)
    victim = it._workers[0]
    time.sleep(1.0)  # let it enter __getitem__
    os.kill(victim.pid, signal.SIGKILL)
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="died unexpectedly"):
        next(it)
    assert time.perf_counter() - t0 < 30, "death detection took too long"


def test_dataloader_drains_in_flight_batch_before_failing():
    """A worker that enqueued its final owed batch (still in the feeder
    pipe) and exited nonzero must NOT be reported as a fatal death: the
    drain pass recovers the batch (dataloader.py __next__ drain branch).

    Deterministic simulation of the put-then-exit race: the first queue
    poll is forced Empty (batch "still in the pipe") while the death check
    reports the worker gone; the drain must then pick the batch up."""
    import queue as queue_mod
    from paddle_tpu.dataloader.dataloader import (_MultiprocessIter,
                                                  default_collate_fn)

    class _FirstPollMisses:
        def __init__(self, q):
            self._q = q
            self._missed = False

        def get(self, timeout=None):
            if not self._missed:
                self._missed = True
                raise queue_mod.Empty
            return self._q.get(timeout=timeout)

        def __getattr__(self, name):
            return getattr(self._q, name)

    it = _MultiprocessIter(_SquaresDataset(2), [[0, 1]], default_collate_fn,
                           num_workers=1)
    # wait out the (slow, 1-core-host) worker start so the batch really is
    # "in the pipe" when the forced-miss poll fires, then re-enqueue it
    in_flight = it._data_queue.get(timeout=60)
    it._data_queue.put(in_flight)
    it._data_queue = _FirstPollMisses(it._data_queue)
    orig = it._abnormal_deaths

    def fake_deaths():
        if 0 in it._received:
            return orig()
        return [(0, 1)]   # "died nonzero, still owing batch 0"

    it._abnormal_deaths = fake_deaths
    feats, squares = next(it)   # must recover via the drain, not raise
    np.testing.assert_allclose(np.asarray(feats).ravel(), [0.0, 1.0])
    np.testing.assert_allclose(np.asarray(squares).ravel(), [0.0, 1.0])


def test_dataloader_normal_completion_not_flagged_as_death():
    """Workers retiring cleanly after the None sentinel must not trip the
    SIGCHLD death path."""
    ds = _SquaresDataset(16)
    dl = paddle.io.DataLoader(ds, batch_size=4, shuffle=False, num_workers=2,
                              use_buffer_reader=False)
    out = np.concatenate([np.asarray(b[0]).ravel() for b in dl])
    np.testing.assert_allclose(out, np.arange(16, dtype=np.float32))


def test_train_from_dataset_steps_per_loop_parity(tmp_path):
    """steps_per_loop=k (one run_steps dispatch per k batches) must produce
    the SAME final parameters as per-step training over the same stream."""
    def build_and_train(steps_per_loop):
        from paddle_tpu.framework import program as pm, scope as sm
        from paddle_tpu.framework import unique_name
        pm._main_program = pm.Program()
        pm._startup_program = pm.Program()
        sm._reset_global_scope()
        unique_name.switch()
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1, name="p")
        loss = layers.reduce_mean(layers.square(pred - y))
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())

        rng = np.random.RandomState(0)
        batches = [{"x": rng.randn(8, 4).astype(np.float32),
                    "y": rng.randn(8, 1).astype(np.float32)}
                   for _ in range(7)]   # 7 = 2 full groups of 3 + tail 1
        out = exe.train_from_dataset(
            fluid.default_main_program(), iter(batches),
            fetch_list=[loss], steps_per_loop=steps_per_loop)
        params = {p.name: np.asarray(fluid.global_scope().find(p.name))
                  for p in fluid.default_main_program().all_parameters()}
        return float(np.asarray(out[0]).reshape(-1)[0]), params

    l1, p1 = build_and_train(1)
    l3, p3 = build_and_train(3)
    assert abs(l1 - l3) < 1e-5, (l1, l3)
    for name in p1:
        np.testing.assert_allclose(p3[name], p1[name], rtol=1e-5,
                                   atol=1e-6)


def test_train_from_dataset_ps_window_groups_batches(tmp_path):
    """Sparse-PS programs ride the grouped run_steps path under
    steps_per_loop>1: ONE pull per k-batch window (counted via the client)
    instead of one per batch, and training still moves the server table."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.ps import (KVServer, SparseTableConfig,
                                           distributed_embedding)

    paths = _write_multislot(tmp_path, n_files=2, rows=16)
    srv = KVServer([SparseTableConfig("wtab", dim=4, init_scale=0.01)])
    port = srv.start(0)
    try:
        x = fluid.layers.data(name="x", shape=[2], dtype="float32")
        ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
        emb = distributed_embedding(ids, "wtab", dim=4, lr=0.1)
        feat = layers.concat([layers.reduce_sum(emb, dim=1), x], axis=1)
        pred = layers.fc(feat, size=1)
        loss = layers.reduce_mean(layers.square(pred))
        fleet.init(role_maker=fleet.UserDefinedRoleMaker(
            server_endpoints=[f"127.0.0.1:{port}"]))
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.01),
            fleet.DistributedStrategy())
        opt.minimize(loss)
        client = fleet.init_worker()

        ds = fluid.dataset.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(8)
        ds.set_thread(1)
        ds.set_use_var([x, ids])
        ds.set_filelist(paths)
        ds.load_into_memory()       # 32 rows -> 4 batches of 8

        hook = fluid.default_main_program()._ps_hooks[0]
        pulls = []
        orig_pull = hook.client.pull
        hook.client.pull = lambda *a, **kw: (pulls.append(1),
                                             orig_pull(*a, **kw))[1]
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        out = exe.train_from_dataset(fluid.default_main_program(), ds,
                                     fetch_list=[loss], steps_per_loop=4)
        assert out is not None and np.isfinite(np.asarray(out[0])).all()
        # 4 batches in ONE window -> exactly 1 pull (per-batch mode would be 4)
        assert len(pulls) == 1, f"expected 1 windowed pull, saw {len(pulls)}"
        t = client.pull(0, np.arange(16, dtype=np.int64), 4)
        assert np.isfinite(t).all()
    finally:
        try:
            fleet.stop_worker()
        except Exception:
            pass
        srv.stop()
