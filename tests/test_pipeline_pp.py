"""True pipeline parallelism over the pp mesh axis (parallel/pipeline.py).

Reference counterpart: PipelineTrainer/SectionWorker multi-device pipeline
(trainer.h:230, section_worker.cc:82) validated there by
test_pipeline.py-style loss-parity runs; here the 8-device virtual CPU mesh
plays the multi-chip role and we assert (a) loss/param parity vs the
single-device run, (b) stage-LOCAL weight placement, (c) stage/mesh
mismatch errors, (d) shared (tied) params across stages get summed grads.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.framework.scope import global_scope
from paddle_tpu.parallel import build_mesh, DistConfig, attach

import jax


pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual CPU mesh")


def _fresh():
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)


def _build_2stage(act="tanh"):
    x = fluid.layers.data(name="x", shape=[6], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    with fluid.device_guard("gpu:0"):
        h = layers.fc(x, size=16, act=act,
                      param_attr=paddle.ParamAttr(name="w0"),
                      bias_attr=paddle.ParamAttr(name="b0"))
    with fluid.device_guard("gpu:1"):
        h2 = layers.fc(h, size=16, act=act,
                       param_attr=paddle.ParamAttr(name="w1"),
                       bias_attr=paddle.ParamAttr(name="b1"))
        pred = layers.fc(h2, size=1,
                         param_attr=paddle.ParamAttr(name="w2"),
                         bias_attr=paddle.ParamAttr(name="b2"))
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    return loss


def _feed(b=16, seed=0):
    rng = np.random.RandomState(seed)
    xb = rng.randn(b, 6).astype(np.float32)
    yb = (np.tanh(xb.sum(1, keepdims=True)) * 0.7).astype(np.float32)
    return {"x": xb, "y": yb}


def _train(mesh_axes, steps=4, micro_k=4, lr=0.1, opt_cls=None):
    """Build + train the 2-stage model; return (losses, w0, w2)."""
    _fresh()
    loss = _build_2stage()
    base = (opt_cls or paddle.optimizer.SGD)(learning_rate=lr)
    opt = paddle.optimizer.PipelineOptimizer(base, num_microbatches=micro_k)
    opt.minimize(loss)
    prog = fluid.default_main_program()
    if mesh_axes:
        n = 1
        for v in mesh_axes.values():
            n *= v
        mesh = build_mesh(dp=mesh_axes.get("dp", 1), tp=mesh_axes.get("tp", 1),
                          pp=mesh_axes.get("pp", 1),
                          devices=jax.devices()[:n])
        attach(prog, DistConfig(mesh=mesh))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = [float(exe.run(prog, feed=_feed(seed=i), fetch_list=[loss])[0])
              for i, _ in enumerate(range(steps))]
    scope = global_scope()
    return losses, np.asarray(scope.find("w0")), np.asarray(scope.find("w2"))


def test_pp2_loss_and_param_parity_vs_single_device():
    pipe_losses, pw0, pw2 = _train({"pp": 2})
    ref_losses, rw0, rw2 = _train({})
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(pw0, rw0, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(pw2, rw2, rtol=1e-4, atol=1e-6)
    assert pipe_losses[-1] < pipe_losses[0], "training did not progress"


def test_pp2_dp2_composes_with_data_parallel():
    pipe_losses, pw0, _ = _train({"pp": 2, "dp": 2})
    ref_losses, rw0, _ = _train({})
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(pw0, rw0, rtol=1e-4, atol=1e-6)


def test_pp2_adam_optimizer_state_stays_stage_local():
    pipe_losses, _, _ = _train({"pp": 2}, opt_cls=paddle.optimizer.Adam,
                               lr=1e-2)
    ref_losses, _, _ = _train({}, opt_cls=paddle.optimizer.Adam, lr=1e-2)
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-6)


def test_pp2_stage_local_weight_placement():
    """Params (and Adam moments) must live ONLY on their stage's submesh."""
    _fresh()
    loss = _build_2stage()
    opt = paddle.optimizer.PipelineOptimizer(
        paddle.optimizer.Adam(learning_rate=1e-2), num_microbatches=2)
    opt.minimize(loss)
    prog = fluid.default_main_program()
    mesh = build_mesh(dp=2, pp=2, devices=jax.devices()[:4])
    attach(prog, DistConfig(mesh=mesh))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(prog, feed=_feed(), fetch_list=[loss])

    from paddle_tpu.parallel.pipeline import _PipelineBlock, stage_devices
    pb = [c for c in exe._cache.values()
          if isinstance(c, _PipelineBlock)][0]
    stage_devs = [set(stage_devices(pb, s)) for s in range(2)]
    scope = global_scope()
    homes = {"w0": 0, "b0": 0, "w1": 1, "b1": 1, "w2": 1, "b2": 1}
    for name, home in homes.items():
        arr = scope.find(name)
        devs = set(arr.sharding.device_set)
        assert devs <= stage_devs[home], (
            f"{name} on {devs}, expected within stage {home} "
            f"submesh {stage_devs[home]}")
        # Adam moments co-locate with their param
        for suffix in ("_moment1_0", "_moment2_0"):
            for cand in (name + suffix, name + ".w_0" + suffix):
                m = scope.find(cand)
                if m is not None:
                    assert set(m.sharding.device_set) <= stage_devs[home]


def test_pp_mesh_stage_count_mismatch_is_typed_error():
    from paddle_tpu.framework import errors
    _fresh()
    loss = _build_2stage()   # 2 stages
    opt = paddle.optimizer.PipelineOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1), num_microbatches=2)
    opt.minimize(loss)
    prog = fluid.default_main_program()
    mesh = build_mesh(dp=1, pp=4, devices=jax.devices()[:4])
    attach(prog, DistConfig(mesh=mesh))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(errors.InvalidArgumentError, match="stage"):
        exe.run(prog, feed=_feed(), fetch_list=[loss])


def test_pp2_shared_param_across_stages_sums_grads():
    """A weight read by BOTH stages (tied-embedding pattern): grads from the
    two stages must sum, matching the single-device run."""

    def build():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[8], dtype="float32")
        shared = fluid.layers.create_parameter(
            [8, 8], "float32", name="wshared")
        with fluid.device_guard("gpu:0"):
            h = layers.tanh(layers.matmul(x, shared))
        with fluid.device_guard("gpu:1"):
            # tied second use (transpose_y like a tied LM head)
            pred = layers.matmul(h, shared, transpose_y=True)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        return loss

    def run(mesh_axes):
        _fresh()
        loss = build()
        opt = paddle.optimizer.PipelineOptimizer(
            paddle.optimizer.SGD(learning_rate=0.05), num_microbatches=2)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        if mesh_axes:
            mesh = build_mesh(dp=1, pp=mesh_axes["pp"],
                              devices=jax.devices()[:mesh_axes["pp"]])
            attach(prog, DistConfig(mesh=mesh))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(3)
        feed = {"x": rng.randn(8, 8).astype(np.float32),
                "y": rng.randn(8, 8).astype(np.float32)}
        losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                  for _ in range(3)]
        return losses, np.asarray(global_scope().find("wshared"))

    pipe_losses, pw = run({"pp": 2})
    ref_losses, rw = run({})
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(pw, rw, rtol=1e-4, atol=1e-6)


def test_gpt_pp2_tied_embeddings_parity():
    """GPT over pp=2: the tied wte is read at stage 0 (lookup) AND the last
    stage (LM head) — the pipeline runner must transfer the table forward
    and sum both stages' grad contributions. Loss/param parity vs the
    single-device GPipe run proves it."""
    from paddle_tpu.models import gpt

    def run(pp):
        _fresh()
        cfg = gpt.GPTConfig.tiny()
        cfg.pipeline_stages = pp if pp > 1 else 0
        tokens, loss = gpt.build_lm_program(cfg)
        opt = paddle.optimizer.PipelineOptimizer(
            paddle.optimizer.Adam(learning_rate=1e-2), num_microbatches=2)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        if pp > 1:
            mesh = build_mesh(dp=1, pp=pp, devices=jax.devices()[:pp])
            attach(prog, DistConfig(mesh=mesh))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        feed = {"tokens": rng.randint(0, cfg.vocab_size,
                                      (8, cfg.seq_len)).astype(np.int64)}
        losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                  for _ in range(3)]
        return losses, np.asarray(global_scope().find("wte"))

    pipe_losses, pw = run(2)
    ref_losses, rw = run(1)
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-4, atol=1e-5)
    # Adam's rsqrt amplifies fusion-reassociation noise on the summed tied
    # grads; the pp and single-device runs group the optimizer ops into
    # different XLA fusions (the shared beta-pow advance is its own opt
    # segment under pp), so the updated table matches to reassociation
    # tolerance, not bit-for-bit
    np.testing.assert_allclose(pw, rw, rtol=5e-4, atol=5e-6)
    assert pipe_losses[-1] < pipe_losses[0]


def test_gpt_pp4_8layers_parity_placement_and_1f1b_window():
    """Four stages streaming >2 sections is where schedules break
    (reference section_worker.cc:82 num_microbatches streaming): GPT-8L
    over pp=4 with tied embeddings must (a) match the single-device GPipe
    run on losses and the tied wte, (b) keep every stage's weights and
    Adam moments stage-LOCAL, and (c) bound the 1F1B window's live
    activation envs at ~S+1 for S=4 — NOT the GPipe drain-everything
    num_microbatches=6."""
    from paddle_tpu.models import gpt
    from paddle_tpu.parallel.pipeline import _PipelineBlock, stage_devices

    S, micro_k = 4, 6

    def run(pp):
        _fresh()
        cfg = gpt.GPTConfig(vocab_size=512, hidden_size=32, num_layers=8,
                            num_heads=2, intermediate_size=64,
                            max_position=32, seq_len=16,
                            hidden_dropout=0.0, attention_dropout=0.0,
                            pipeline_stages=pp if pp > 1 else 0)
        tokens, loss = gpt.build_lm_program(cfg)
        opt = paddle.optimizer.PipelineOptimizer(
            paddle.optimizer.Adam(learning_rate=1e-2),
            num_microbatches=micro_k)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        if pp > 1:
            mesh = build_mesh(dp=1, pp=pp, devices=jax.devices()[:pp])
            attach(prog, DistConfig(mesh=mesh))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        feed = {"tokens": rng.randint(0, cfg.vocab_size,
                                      (12, cfg.seq_len)).astype(np.int64)}
        losses = [float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
                  for _ in range(3)]
        return exe, losses, np.asarray(global_scope().find("wte"))

    exe, pipe_losses, pw = run(S)
    pb = [c for c in exe._cache.values() if isinstance(c, _PipelineBlock)][0]
    assert pb.num_stages == S

    # (c) 1F1B live-activation bound: at most S+1 envs ever live, and the
    # steady state actually reaches the S-deep window (not running
    # sequentially with window 1)
    assert pb.last_max_live_envs <= S + 1, pb.last_max_live_envs
    assert pb.last_max_live_envs >= S, pb.last_max_live_envs

    # (b) stage-local placement: one sampled weight + its Adam moments per
    # stage must live within that stage's submesh (8 layers / 4 stages ->
    # layers 2m,2m+1 on stage m); the tied wte homes at its first reader
    # (stage 0)
    scope = global_scope()
    stage_devs = [set(stage_devices(pb, s)) for s in range(S)]
    homes = {f"dec{2 * s}_attn_qkv_w": s for s in range(S)}
    homes["wte"] = 0
    for name, home in homes.items():
        arr = scope.find(name)
        assert arr is not None, name
        devs = set(arr.sharding.device_set)
        assert devs <= stage_devs[home], (
            f"{name} on {devs}, expected within stage {home}")
        for suffix in ("_moment1_0", "_moment2_0"):
            m = scope.find(name + suffix)
            if m is not None:
                assert set(m.sharding.device_set) <= stage_devs[home], \
                    name + suffix

    # (a) parity vs the single-device GPipe schedule (tied wte read at
    # stage 0 AND stage 3: the runner must transfer it forward and sum
    # both stages' grad contributions across the 4-deep pipeline)
    _exe, ref_losses, rw = run(1)
    np.testing.assert_allclose(pipe_losses, ref_losses,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(pw, rw, rtol=5e-4, atol=5e-6)
    assert pipe_losses[-1] < pipe_losses[0]
