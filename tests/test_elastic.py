"""Preemption guard + elastic (mesh-resize) resume
(reference auto_checkpoint tests: test_auto_checkpoint.py; slice-resize is
TPU-native — SURVEY §5 failure-detection row)."""
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from conftest import cpu_mesh_env

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.incubate.elastic import PreemptionGuard


def _build_quadratic():
    w = layers.create_parameter(
        [4], "float32", name="w",
        default_initializer=paddle.initializer.Constant(4.0))
    loss = layers.reduce_mean(layers.square(w))
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_steps_resume_after_restart(tmp_path):
    loss = _build_quadratic()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    g = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
    seen = []
    for step in g.steps(6, save_interval=2):
        exe.run(fetch_list=[loss])
        seen.append(step)
    assert seen == list(range(6))
    w_after_6 = np.asarray(fluid.global_scope().find("w")).copy()

    # "restart": fresh scope, same program; resume must skip all 6 steps
    from paddle_tpu.framework import scope as sm
    sm._reset_global_scope()
    g2 = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
    resumed = list(g2.steps(6, save_interval=2))
    assert resumed == []
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find("w")), w_after_6)


_PREEMPT_PROG = """
import os, sys, time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.incubate.elastic import PreemptionGuard

w = layers.create_parameter([4], "float32", name="w",
    default_initializer=paddle.initializer.Constant(4.0))
loss = layers.reduce_mean(layers.square(w))
paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
g = PreemptionGuard(sys.argv[1])
print("READY", flush=True)
for step in g.steps(10_000, save_interval=1_000_000):
    exe.run(fetch_list=[loss])
    print("STEP", step, flush=True)
    time.sleep(0.05)
print("FINISHED", flush=True)
"""


def test_sigterm_checkpoints_and_exits_143(tmp_path):
    ckpt = str(tmp_path / "ck")
    proc = subprocess.Popen(
        [sys.executable, "-c", _PREEMPT_PROG, ckpt],
        env=cpu_mesh_env(1), stdout=subprocess.PIPE, text=True)
    # wait until it is mid-loop, then deliver the preemption notice
    deadline = time.time() + 120
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "":          # EOF: child died before reaching step 2
            assert proc.poll() is None, (proc.returncode, lines)
            break
        lines.append(line)
        if line.startswith("STEP 2"):
            break
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 143, (lines, out)
    assert "FINISHED" not in out
    # the final checkpoint exists and holds a trained w
    g = PreemptionGuard(ckpt, exit_on_preempt=False)
    path, meta = g.saver.latest()
    assert path is not None and meta["step"] >= 2


def test_resume_on_smaller_mesh(tmp_path):
    """Elastic slice-resize: checkpoint on a dp=4 mesh, resume on dp=2 —
    full-host-array checkpoints + GSPMD resharding make the layout a
    property of the EXECUTION, not the checkpoint."""
    code = textwrap.dedent("""
        import sys
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import layers
        from paddle_tpu.parallel import build_mesh, DistConfig, attach
        from paddle_tpu.incubate.elastic import PreemptionGuard

        dp, ckpt, nsteps = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
        attach(fluid.default_main_program(), DistConfig(
            mesh=build_mesh(dp=dp)))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        xv = rng.randn(16, 8).astype(np.float32)
        w_true = rng.randn(8, 1).astype(np.float32)
        yv = (xv @ w_true).astype(np.float32)
        g = PreemptionGuard(ckpt, exit_on_preempt=False)
        total = int(sys.argv[4])
        vals = []
        for step in g.steps(total, save_interval=nsteps):
            out, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
            vals.append(float(np.asarray(out).reshape(-1)[0]))
        print("LOSSES", ",".join(f"{v:.6f}" for v in vals), flush=True)
    """)
    ckpt = str(tmp_path / "ck")

    def run(dp, n_done, total, n_devices):
        r = subprocess.run(
            [sys.executable, "-c", code, str(dp), ckpt, str(n_done),
             str(total)],
            env=cpu_mesh_env(n_devices), capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stderr
        for line in r.stdout.splitlines():
            if line.startswith("LOSSES"):
                payload = line.split(" ", 1)[1] if " " in line else ""
                return [float(v) for v in payload.split(",") if v]
        return []

    first = run(dp=4, n_done=6, total=6, n_devices=4)
    assert len(first) == 6 and first[-1] < first[0]
    # resume the SAME job on a dp=2 mesh: picks up at step 6, keeps falling
    second = run(dp=2, n_done=6, total=12, n_devices=2)
    assert len(second) == 6, second
    assert second[0] < first[-1] * 1.01
    assert second[-1] < second[0]

    # single-process parity oracle: 12 uninterrupted steps reach the same
    # loss trajectory the resized job did
    import shutil
    shutil.rmtree(ckpt)
    straight = run(dp=1, n_done=100, total=12, n_devices=1)
    np.testing.assert_allclose(straight[6:], second, rtol=1e-4, atol=1e-6)
