"""Preemption guard + elastic (mesh-resize) resume
(reference auto_checkpoint tests: test_auto_checkpoint.py; slice-resize is
TPU-native — SURVEY §5 failure-detection row).

The ZeRO-aware half (docs/resilience.md "Elasticity & preemption"): a
checkpoint written under dp=N sharded state must resume under dp=M with
bit-for-bit parity against a replicated resume from the SAME checkpoint —
the flat-bucket repack of `zero.adopt_unsharded_state` is the unit under
test, driven through subprocesses on a 4-device CPU mesh."""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from conftest import cpu_mesh_env

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.incubate.elastic import PreemptionGuard


def _build_quadratic():
    w = layers.create_parameter(
        [4], "float32", name="w",
        default_initializer=paddle.initializer.Constant(4.0))
    loss = layers.reduce_mean(layers.square(w))
    paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_steps_resume_after_restart(tmp_path):
    loss = _build_quadratic()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    g = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
    seen = []
    for step in g.steps(6, save_interval=2):
        exe.run(fetch_list=[loss])
        seen.append(step)
    assert seen == list(range(6))
    w_after_6 = np.asarray(fluid.global_scope().find("w")).copy()

    # "restart": fresh scope, same program; resume must skip all 6 steps
    from paddle_tpu.framework import scope as sm
    sm._reset_global_scope()
    g2 = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
    resumed = list(g2.steps(6, save_interval=2))
    assert resumed == []
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find("w")), w_after_6)


_PREEMPT_PROG = """
import os, sys, time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.incubate.elastic import PreemptionGuard

w = layers.create_parameter([4], "float32", name="w",
    default_initializer=paddle.initializer.Constant(4.0))
loss = layers.reduce_mean(layers.square(w))
paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
g = PreemptionGuard(sys.argv[1])
print("READY", flush=True)
for step in g.steps(10_000, save_interval=1_000_000):
    exe.run(fetch_list=[loss])
    print("STEP", step, flush=True)
    time.sleep(0.05)
print("FINISHED", flush=True)
"""


def test_sigterm_checkpoints_and_exits_143(tmp_path):
    ckpt = str(tmp_path / "ck")
    proc = subprocess.Popen(
        [sys.executable, "-c", _PREEMPT_PROG, ckpt],
        env=cpu_mesh_env(1), stdout=subprocess.PIPE, text=True)
    # wait until it is mid-loop, then deliver the preemption notice
    deadline = time.time() + 120
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "":          # EOF: child died before reaching step 2
            assert proc.poll() is None, (proc.returncode, lines)
            break
        lines.append(line)
        if line.startswith("STEP 2"):
            break
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 143, (lines, out)
    assert "FINISHED" not in out
    # the final checkpoint exists and holds a trained w
    g = PreemptionGuard(ckpt, exit_on_preempt=False)
    path, meta = g.saver.latest()
    assert path is not None and meta["step"] >= 2


def test_resume_on_smaller_mesh(tmp_path):
    """Elastic slice-resize: checkpoint on a dp=4 mesh, resume on dp=2 —
    full-host-array checkpoints + GSPMD resharding make the layout a
    property of the EXECUTION, not the checkpoint."""
    code = textwrap.dedent("""
        import sys
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import layers
        from paddle_tpu.parallel import build_mesh, DistConfig, attach
        from paddle_tpu.incubate.elastic import PreemptionGuard

        dp, ckpt, nsteps = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.mean(layers.square_error_cost(pred, y))
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
        attach(fluid.default_main_program(), DistConfig(
            mesh=build_mesh(dp=dp)))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        xv = rng.randn(16, 8).astype(np.float32)
        w_true = rng.randn(8, 1).astype(np.float32)
        yv = (xv @ w_true).astype(np.float32)
        g = PreemptionGuard(ckpt, exit_on_preempt=False)
        total = int(sys.argv[4])
        vals = []
        for step in g.steps(total, save_interval=nsteps):
            out, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
            vals.append(float(np.asarray(out).reshape(-1)[0]))
        print("LOSSES", ",".join(f"{v:.6f}" for v in vals), flush=True)
    """)
    ckpt = str(tmp_path / "ck")

    def run(dp, n_done, total, n_devices):
        r = subprocess.run(
            [sys.executable, "-c", code, str(dp), ckpt, str(n_done),
             str(total)],
            env=cpu_mesh_env(n_devices), capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stderr
        for line in r.stdout.splitlines():
            if line.startswith("LOSSES"):
                payload = line.split(" ", 1)[1] if " " in line else ""
                return [float(v) for v in payload.split(",") if v]
        return []

    first = run(dp=4, n_done=6, total=6, n_devices=4)
    assert len(first) == 6 and first[-1] < first[0]
    # resume the SAME job on a dp=2 mesh: picks up at step 6, keeps falling
    second = run(dp=2, n_done=6, total=12, n_devices=2)
    assert len(second) == 6, second
    assert second[0] < first[-1] * 1.01
    assert second[-1] < second[0]

    # single-process parity oracle: 12 uninterrupted steps reach the same
    # loss trajectory the resized job did
    import shutil
    shutil.rmtree(ckpt)
    straight = run(dp=1, n_done=100, total=12, n_devices=1)
    np.testing.assert_allclose(straight[6:], second, rtol=1e-4, atol=1e-6)


# --- ZeRO-aware dp-resize resume -----------------------------------------
# One subprocess, three arms per configuration (the
# test_collective_budget.py pattern): train dp=4 ZeRO -> portable
# checkpoint -> resume dp=2 ZeRO (the flat-bucket repack under test) vs
# resume dp=2 REPLICATED from the same checkpoint (the oracle). Bit-for-bit
# on losses AND every portable persistable.

_RESIZE_COMMON = """
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.distributed import fleet
from paddle_tpu.models import bert
from paddle_tpu.testing import (reset_programs, zero_resize_attach,
                                zero_resize_case,
                                zero_resize_flat_build as build_flat)


def build_rolled(dp, stage):
    reset_programs(0)
    cfg = bert.BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position=32, seq_len=16, hidden_dropout=0.0,
                          attention_dropout=0.0)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.layer_scan = True                   # @LAYERS [L, padded] shards
    if stage:
        s.sharding_stage = stage
    s.fuse_grad_size_in_mb = 0.05
    fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-3), s).minimize(loss)
    prog = fluid.default_main_program()
    zero_resize_attach(prog, dp)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    def feed(step):
        rng = np.random.RandomState(200 + step)
        return {"input_ids":
                    rng.randint(0, 64, (8, 16)).astype(np.int64),
                "mlm_labels":
                    rng.randint(0, 64, (8, 16, 1)).astype(np.int64)}

    return exe, prog, loss, feed


resize_case = zero_resize_case
"""


def _run_resize(code: str, n_devices=4, timeout=900) -> dict:
    r = subprocess.run([sys.executable, "-c",
                        _RESIZE_COMMON + textwrap.dedent(code)],
                       env=cpu_mesh_env(n_devices), capture_output=True,
                       text=True, timeout=timeout)
    assert r.returncode == 0, f"subprocess failed:\n{r.stdout}\n{r.stderr}"
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_zero_dp_resize_resume_stages():
    """dp=4 -> dp=2 resume through ZeRO stages 1/2/3 (flat buckets) plus
    the stage-3 x rolled-@LAYERS composition ([L, padded] trailing-axis
    shards), each bit-identical to a replicated dp=2 resume from the SAME
    portable checkpoint."""
    out = _run_resize("""
res = {}
for stage in (1, 2, 3):
    res[f"flat{stage}"] = resize_case(build_flat, stage)
res["rolled3"] = resize_case(build_rolled, 3)
print(json.dumps(res))
""")
    for case, r in out.items():
        assert r["losses_equal"], (case, r["l_zero"], r["l_repl"])
        assert r["mismatched"] == [], (case, r["mismatched"])


@pytest.mark.slow
def test_zero_dp_resize_resume_sweeps():
    """Heavier resize matrix: rolled stages 1/2, a dp=4 -> dp=3 resume
    whose width does not divide the 64-element bucket padding (must take
    the full-width replicated fallback and STILL match), and a grow
    (dp=2 -> dp=4) through stage 3."""
    out = _run_resize("""
res = {"rolled1": resize_case(build_rolled, 1),
       "rolled2": resize_case(build_rolled, 2),
       "flat3_to_dp3": resize_case(build_flat, 3, dp_from=4, dp_to=3),
       "flat3_grow": resize_case(build_flat, 3, dp_from=2, dp_to=4)}
print(json.dumps(res))
""")
    for case, r in out.items():
        assert r["losses_equal"], (case, r["l_zero"], r["l_repl"])
        assert r["mismatched"] == [], (case, r["mismatched"])


# --- PreemptionGuard handler hygiene -------------------------------------

def test_preemption_guard_uninstall_restores_handlers(tmp_path):
    """uninstall() (and the context-manager form) must restore the
    previous SIGTERM/SIGUSR1 handlers — a guard may never leak its handler
    past its trainer's lifetime."""
    def custom(signum, frame):
        pass

    prev_term = signal.signal(signal.SIGTERM, custom)
    prev_usr1 = signal.getsignal(signal.SIGUSR1)
    try:
        with PreemptionGuard(str(tmp_path), exit_on_preempt=False) as g:
            assert signal.getsignal(signal.SIGTERM) == g._on_signal
            assert signal.getsignal(signal.SIGUSR1) == g._on_signal
        assert signal.getsignal(signal.SIGTERM) is custom
        assert signal.getsignal(signal.SIGUSR1) == prev_usr1
        g.uninstall()                       # idempotent
        assert signal.getsignal(signal.SIGTERM) is custom

        # a handler someone installed OVER the guard's must survive the
        # guard's uninstall (restore only what is still ours)
        g2 = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        g2.uninstall()
        assert signal.getsignal(signal.SIGTERM) == signal.SIG_IGN
        assert signal.getsignal(signal.SIGUSR1) == prev_usr1
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGUSR1, prev_usr1)


def test_preemption_guard_chains_previous_handler(tmp_path):
    """A surviving pre-existing handler still fires through the guard's."""
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    try:
        with PreemptionGuard(str(tmp_path), exit_on_preempt=False) as g:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.time() + 5
            while not hits and time.time() < deadline:
                time.sleep(0.01)
            assert g.preempted.is_set()
            assert hits == [signal.SIGUSR1]
    finally:
        signal.signal(signal.SIGUSR1, prev)


# --- crash-safe saves on the preemption path ------------------------------

def test_saver_torn_latest_falls_back(tmp_path):
    """A kill landing mid-final-save may tear the newest checkpoint; the
    incubate CheckpointSaver (now CheckpointManager-backed) must fall back
    to the previous COMPLETE one instead of serving torn state."""
    from paddle_tpu.incubate.checkpoint import CheckpointSaver, load_state
    s = CheckpointSaver(str(tmp_path), max_num=3)
    good = np.arange(4, dtype=np.float32)
    assert s.save({"w": good}, {"step": 3}) == 3
    assert s.save({"w": np.full(4, 9.0, np.float32)}, {"step": 6}) == 6
    path, meta = s.latest()
    assert meta["step"] == 6
    # tear the published step-6 data: checksum validation must reject it
    with open(path, "r+b") as f:
        f.write(b"torn bytes")
    path2, meta2 = s.latest()
    assert meta2["step"] == 3, meta2
    np.testing.assert_array_equal(load_state(path2)["w"], good)

    # a mid-save SIGKILL leaves only an unpublished tmp dir: ignored
    os.makedirs(os.path.join(str(tmp_path), "ckpt_9.tmp.12345"))
    _, meta3 = s.latest()
    assert meta3["step"] == 3


def test_guard_restore_skips_torn_checkpoint(tmp_path):
    """End-to-end on PreemptionGuard: restore() must resume from the last
    complete checkpoint when the newest one is torn."""
    g = PreemptionGuard(str(tmp_path), exit_on_preempt=False)
    try:
        from paddle_tpu.framework import scope as sm
        sm._reset_global_scope()
        loss = _build_quadratic()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        exe.run(fetch_list=[loss])
        g.checkpoint_now(4)
        w4 = np.asarray(fluid.global_scope().find("w")).copy()
        exe.run(fetch_list=[loss])
        g.checkpoint_now(9)
        path, _ = g.saver.latest()
        with open(path, "r+b") as f:
            f.write(b"torn bytes")
        sm._reset_global_scope()
        assert g.restore() == 5        # step-9 save is torn -> resume at 5
        np.testing.assert_array_equal(
            np.asarray(fluid.global_scope().find("w")), w4)
    finally:
        g.uninstall()


# --- incubate train_epoch_range (reference auto_checkpoint parity) --------

def _epoch_run(n_epochs):
    """One trainer life: fresh programs/scope, startup init, then the
    resumable epoch range. Returns (epochs seen, final w)."""
    from paddle_tpu.incubate.checkpoint import train_epoch_range
    from paddle_tpu.testing import reset_programs
    reset_programs(0)
    loss = _build_quadratic()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    seen = []
    for epoch in train_epoch_range(n_epochs):
        exe.run(fetch_list=[loss])
        seen.append(epoch)
    return seen, np.asarray(fluid.global_scope().find("w")).copy()


def test_train_epoch_range_resumes_bit_for_bit(tmp_path, monkeypatch):
    """The epoch loop the reference auto_checkpoint.py wraps: a restart
    with the same job id resumes AFTER the last completed epoch, a torn
    newest save falls back one epoch, and the resumed trajectory is
    bit-identical to an uninterrupted run; without the env contract the
    range degrades to plain range()."""
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "LOCAL")
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_JOB_ID", "job7")

    first, _ = _epoch_run(3)
    assert first == [0, 1, 2]
    # "restart": fresh scope + programs; picks up at epoch 3
    resumed, w_resumed = _epoch_run(5)
    assert resumed == [3, 4]

    # no env contract -> plain range(); also the 5-epoch oracle
    monkeypatch.delenv("PADDLE_RUNNING_ENV")
    straight, w_straight = _epoch_run(5)
    assert straight == [0, 1, 2, 3, 4]
    np.testing.assert_array_equal(w_resumed, w_straight)
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "LOCAL")

    # tear the newest save (epoch 4): the next life must fall back to the
    # epoch-3 checkpoint and re-run epoch 4, not serve torn state
    from paddle_tpu.incubate.checkpoint import CheckpointSaver
    saver = CheckpointSaver(str(tmp_path / "job7"))
    path, meta = saver.latest()
    assert meta["epoch"] == 4
    with open(path, "r+b") as f:
        f.write(b"torn bytes")
    resumed2, w2 = _epoch_run(5)
    assert resumed2 == [4]
    np.testing.assert_array_equal(w2, w_straight)


def test_train_epoch_range_reads_legacy_ptck(tmp_path, monkeypatch):
    """Pre-CheckpointManager checkpoints (ckpt_<v>/state.ptck + meta.json,
    the native threaded-IO layout) still resume: CheckpointSaver.latest()
    falls through manifest validation to the legacy reader."""
    from paddle_tpu.native.ckptio import save_tensors

    from paddle_tpu import monitor
    from paddle_tpu.incubate.checkpoint import CheckpointSaver

    legacy = tmp_path / "legacy" / "ckpt_1"
    os.makedirs(legacy)
    w_saved = np.full(4, 2.5, np.float32)
    save_tensors(str(legacy / "state.ptck"), {"w": w_saved})
    with open(legacy / "meta.json", "w") as f:
        json.dump({"epoch": 1}, f)

    # an OLDER manager-format save must not shadow the newer legacy dir,
    # and walking past healthy legacy dirs must not count as a torn-save
    # fallback (resilience.ckpt_fallbacks is the torn-MANAGER-save stat)
    saver = CheckpointSaver(str(tmp_path / "legacy"))
    saver._mgr.save(0, arrays={"w": np.zeros(4, np.float32)},
                    meta={"epoch": 0})
    monitor.stat_reset("resilience.ckpt_fallbacks")
    path, meta = saver.latest()
    assert path.endswith("state.ptck") and meta["epoch"] == 1
    assert monitor.stat_get("resilience.ckpt_fallbacks") == 0

    monkeypatch.setenv("PADDLE_RUNNING_ENV", "LOCAL")
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_JOB_ID", "legacy")
    from paddle_tpu.incubate.checkpoint import train_epoch_range
    from paddle_tpu.testing import reset_programs
    reset_programs(0)
    loss = _build_quadratic()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())      # w re-inits to 4.0 ...
    seen = []
    for epoch in train_epoch_range(4):
        if not seen:                              # ... restore overrode it
            np.testing.assert_array_equal(
                np.asarray(fluid.global_scope().find("w")), w_saved)
        exe.run(fetch_list=[loss])
        seen.append(epoch)
    assert seen == [2, 3]
    # the new saves land in the crash-safe manager format and, being
    # newer, now win the walk
    _, meta = CheckpointSaver(str(tmp_path / "legacy")).latest()
    assert meta["epoch"] == 3


# --- step-level hang watchdog --------------------------------------------

def test_step_deadline_watchdog_trips():
    """FLAGS_step_deadline_ms's engine: a call that outlives the deadline
    raises the typed DeadlineExceededError carrying a thread-stack dump and
    counts executor.step_deadline_trips; fast calls pass values and
    exceptions through unchanged."""
    from paddle_tpu import monitor
    from paddle_tpu.framework import errors
    from paddle_tpu.framework.executor import _deadline_call
    monitor.stat_reset("executor.step_deadline_trips")

    with pytest.raises(errors.DeadlineExceededError) as ei:
        _deadline_call(lambda: time.sleep(30), 150.0, "unit probe")
    msg = str(ei.value)
    assert "unit probe" in msg and "thread stacks" in msg
    assert "executor-step" in msg          # the wedged thread is in the dump
    assert monitor.stat_get("executor.step_deadline_trips") == 1

    assert _deadline_call(lambda: 42, 5000.0, "fast") == 42

    def boom():
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        _deadline_call(boom, 5000.0, "raise")
    assert monitor.stat_get("executor.step_deadline_trips") == 1


def test_step_deadline_passthrough_parity():
    """With the watchdog armed but not tripping, a training step returns
    the same value as with it off (the default) — the deadline path must
    be a pure wrapper."""
    from paddle_tpu import monitor
    from paddle_tpu.flags import set_flags
    from paddle_tpu.framework import scope as sm

    def one_run():
        sm._reset_global_scope()
        from paddle_tpu.framework import program as pm
        from paddle_tpu.framework import unique_name
        pm._main_program = pm.Program()
        pm._startup_program = pm.Program()
        unique_name.switch()
        loss = _build_quadratic()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        vals = [float(np.asarray(exe.run(fetch_list=[loss])[0]).ravel()[0])
                for _ in range(3)]
        return vals

    monitor.stat_reset("executor.step_deadline_trips")
    base = one_run()
    set_flags({"FLAGS_step_deadline_ms": 60000.0})
    try:
        armed = one_run()
    finally:
        set_flags({"FLAGS_step_deadline_ms": 0.0})
    assert armed == base
    assert monitor.stat_get("executor.step_deadline_trips") == 0
