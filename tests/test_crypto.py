"""Model encryption (reference framework/io/crypto/: aes_cipher_test.cc,
cipher_utils_test.cc patterns): FIPS test vectors for the primitives, AEAD
round-trip/tamper/wrong-key behavior, key utils, and an encrypted
inference-model round trip through the Predictor."""
import ctypes
import os

import numpy as np
import pytest

from paddle_tpu.crypto import (AESCipher, CipherFactory, CipherUtils,
                               decrypt_inference_model,
                               encrypt_inference_model)


def _raw():
    from paddle_tpu.native import load_native
    lib = load_native("crypto")
    if lib is None:
        pytest.skip("toolchain unavailable")
    return lib


def test_sha256_fips_vector():
    lib = _raw()
    out = ctypes.create_string_buffer(32)
    lib.pd_crypto_sha256(b"abc", 3, out)
    assert out.raw.hex() == ("ba7816bf8f01cfea414140de5dae2223"
                             "b00361a396177a9cb410ff61f20015ad")
    lib.pd_crypto_sha256(b"", 0, out)
    assert out.raw.hex() == ("e3b0c44298fc1c149afbf4c8996fb924"
                             "27ae41e4649b934ca495991b7852b855")


def test_aes_fips197_vectors():
    """FIPS-197 appendix C block-cipher vectors."""
    lib = _raw()
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    out = ctypes.create_string_buffer(16)
    key128 = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    assert lib.pd_crypto_aes_block(key128, 128, pt, out) == 0
    assert out.raw.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    key256 = bytes.fromhex("000102030405060708090a0b0c0d0e0f"
                           "101112131415161718191a1b1c1d1e1f")
    assert lib.pd_crypto_aes_block(key256, 256, pt, out) == 0
    assert out.raw.hex() == "8ea2b7ca516745bfeafc49904b496089"


@pytest.mark.parametrize("bits", [128, 256])
def test_roundtrip_and_iv_freshness(bits):
    c = AESCipher(bits)
    key = CipherUtils.gen_key(256)
    msg = os.urandom(1000) + b"tail"
    ct1 = c.encrypt(msg, key)
    ct2 = c.encrypt(msg, key)
    assert len(ct1) == len(msg) + 48
    assert ct1 != ct2, "IV must be fresh per encryption"
    assert c.decrypt(ct1, key) == msg
    assert c.decrypt(ct2, key) == msg
    assert msg not in ct1


def test_tamper_and_wrong_key_detected():
    c = AESCipher()
    key = CipherUtils.gen_key(128)
    ct = bytearray(c.encrypt(b"model bytes", key))
    ct[20] ^= 1                                   # flip a ciphertext bit
    with pytest.raises(ValueError, match="tag mismatch"):
        c.decrypt(bytes(ct), key)
    ct[20] ^= 1                                   # restore
    with pytest.raises(ValueError, match="tag mismatch"):
        c.decrypt(bytes(ct), CipherUtils.gen_key(128))
    assert c.decrypt(bytes(ct), key) == b"model bytes"


def test_cipher_utils_and_factory(tmp_path):
    kf = str(tmp_path / "k.bin")
    k = CipherUtils.gen_key_to_file(256, kf)
    assert len(k) == 32 and CipherUtils.read_key_from_file(kf) == k
    cfgf = str(tmp_path / "cipher.conf")
    with open(cfgf, "w") as f:
        f.write("# comment\ncipher_name=AES_CTR_NoPadding\naes_key_bits"
                "=128\n")
    c = CipherFactory.create_cipher(cfgf)
    assert c.bits == 128
    assert CipherFactory.create_cipher().bits == 256


def test_encrypted_inference_model_roundtrip(tmp_path):
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = layers.data(name="x", shape=[4], dtype="float32")
    p = layers.fc(x, 2)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "m")
    fluid.io.save_inference_model(d, ["x"], [p], exe)
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)

    from paddle_tpu.inference import Config, Predictor
    ref = Predictor(Config(d))
    ref.get_input_handle("x").copy_from_cpu(xv)
    want = np.asarray(ref.run()[0])

    key = CipherUtils.gen_key(256)
    encrypt_inference_model(d, key)
    assert not os.path.exists(os.path.join(d, "__model__"))
    with pytest.raises(Exception):
        Predictor(Config(d))                  # at-rest form is unreadable

    decrypt_inference_model(d, key)
    pred = Predictor(Config(d))
    pred.get_input_handle("x").copy_from_cpu(xv)
    np.testing.assert_allclose(np.asarray(pred.run()[0]), want, rtol=1e-6)
