"""MoE / expert parallelism (beyond-reference capability making
expert_parallel_degree real): op semantics, training, and ep-sharded parity
on the 8-device CPU mesh."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np

from conftest import cpu_mesh_env

import paddle_tpu  # noqa: F401
from op_test import run_op

R = np.random.RandomState(0)


def _moe_ins(n=8, d=4, e=2, ff=8):
    return {
        "X": [R.randn(n, d).astype(np.float32)],
        "GateW": [R.randn(d, e).astype(np.float32)],
        "ExpertW1": [R.randn(e, d, ff).astype(np.float32)],
        "ExpertB1": [np.zeros((e, ff), np.float32)],
        "ExpertW2": [R.randn(e, ff, d).astype(np.float32)],
        "ExpertB2": [np.zeros((e, d), np.float32)],
    }


def test_single_expert_equals_dense_ffn():
    ins = _moe_ins(e=1)
    # capacity 1.0 * N / 1 = N: nothing drops, gate prob = 1
    out = np.asarray(run_op("switch_moe", ins,
                            {"capacity_factor": 1.0})["Out"][0])
    x = ins["X"][0]
    ref = np.maximum(x @ ins["ExpertW1"][0][0], 0) @ ins["ExpertW2"][0][0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_capacity_drops_overflow_tokens():
    ins = _moe_ins(n=8, e=2)
    # force every token to expert 0 via a huge gate column
    ins["GateW"] = [np.zeros((4, 2), np.float32)]
    ins["GateW"][0][:, 0] = 100.0
    ins["X"][0][:] = np.abs(ins["X"][0])  # positive x -> huge col-0 logits
    out = run_op("switch_moe", ins, {"capacity_factor": 0.5})
    gidx = np.asarray(out["GateIdx"][0])
    assert (gidx == 0).all()
    o = np.asarray(out["Out"][0])
    # capacity = ceil(8/2*0.5)=2: tokens beyond the first 2 output zero
    assert np.abs(o[2:]).max() == 0.0
    assert np.abs(o[:2]).max() > 0.0


def test_moe_layer_trains():
    out = subprocess.run([sys.executable, "-c", textwrap.dedent("""
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
paddle.seed(0)
x = fluid.layers.data(name="x", shape=[16], dtype="float32")
y = fluid.layers.data(name="y", shape=[1], dtype="float32")
h, aux = layers.switch_moe(x, num_experts=4, d_ff=32)
pred = layers.fc(h, 1)
loss = layers.mean(layers.square_error_cost(pred, y)) + 0.01 * aux
paddle.optimizer.Adam(learning_rate=0.01).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
rng = np.random.RandomState(0)
xs = rng.randn(64, 16).astype(np.float32)
ys = np.tanh(xs.sum(1, keepdims=True) * 0.3).astype(np.float32)
losses = []
for _ in range(40):
    lv, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
    losses.append(float(lv))
print(json.dumps({"first": losses[0], "last": losses[-1]}))
""")], env=cpu_mesh_env(8), capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["last"] < res["first"] * 0.7


def test_ep_sharded_matches_unsharded():
    """ep=4 expert-sharded run must produce the same losses as unsharded —
    GSPMD all-to-all dispatch is numerics-preserving."""
    code = textwrap.dedent("""
import json
import numpy as np
import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel.mesh import moe_sharding_rules

def run(ep):
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=3)
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h, aux = layers.switch_moe(x, num_experts=4, d_ff=32)
    pred = layers.fc(h, 1)
    loss = layers.mean(layers.square_error_cost(pred, y)) + 0.01 * aux
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.expert_parallel_degree = ep
    if ep > 1:
        s.tensor_parallel_rules = moe_sharding_rules()
    opt = fleet.distributed_optimizer(paddle.optimizer.SGD(0.05), s)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.tanh(xs.sum(1, keepdims=True) * 0.3).astype(np.float32)
    return [float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
            for _ in range(6)]

plain = run(1)
sharded = run(4)
print(json.dumps({"plain": plain, "sharded": sharded}))
""")
    out = subprocess.run([sys.executable, "-c", code], env=cpu_mesh_env(8),
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr
    res = json.loads(out.stdout.strip().splitlines()[-1])
    np.testing.assert_allclose(res["sharded"], res["plain"],
                               rtol=2e-4, atol=2e-5)
    assert res["plain"][-1] < res["plain"][0]
