"""MoE / expert parallelism (beyond-reference capability making
expert_parallel_degree real): op semantics, training, and ep-sharded parity
on the 8-device CPU mesh."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from conftest import cpu_mesh_env

import paddle_tpu  # noqa: F401
from op_test import run_op

R = np.random.RandomState(0)


def _moe_ins(n=8, d=4, e=2, ff=8):
    return {
        "X": [R.randn(n, d).astype(np.float32)],
        "GateW": [R.randn(d, e).astype(np.float32)],
        "ExpertW1": [R.randn(e, d, ff).astype(np.float32)],
        "ExpertB1": [np.zeros((e, ff), np.float32)],
        "ExpertW2": [R.randn(e, ff, d).astype(np.float32)],
        "ExpertB2": [np.zeros((e, d), np.float32)],
    }


def test_single_expert_equals_dense_ffn():
    ins = _moe_ins(e=1)
    # capacity 1.0 * N / 1 = N: nothing drops, gate prob = 1
    out = np.asarray(run_op("switch_moe", ins,
                            {"capacity_factor": 1.0})["Out"][0])
    x = ins["X"][0]
    ref = np.maximum(x @ ins["ExpertW1"][0][0], 0) @ ins["ExpertW2"][0][0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_capacity_drops_overflow_tokens():
    ins = _moe_ins(n=8, e=2)
    # force every token to expert 0 via a huge gate column
    ins["GateW"] = [np.zeros((4, 2), np.float32)]
    ins["GateW"][0][:, 0] = 100.0
    ins["X"][0][:] = np.abs(ins["X"][0])  # positive x -> huge col-0 logits
    out = run_op("switch_moe", ins, {"capacity_factor": 0.5})
    gidx = np.asarray(out["GateIdx"][0])
    assert (gidx == 0).all()
    o = np.asarray(out["Out"][0])
    # capacity = ceil(8/2*0.5)=2: tokens beyond the first 2 output zero
    assert np.abs(o[2:]).max() == 0.0
    assert np.abs(o[:2]).max() > 0.0


def test_moe_layer_trains():
    out = subprocess.run([sys.executable, "-c", textwrap.dedent("""
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
paddle.seed(0)
x = fluid.layers.data(name="x", shape=[16], dtype="float32")
y = fluid.layers.data(name="y", shape=[1], dtype="float32")
h, aux = layers.switch_moe(x, num_experts=4, d_ff=32)
pred = layers.fc(h, 1)
loss = layers.mean(layers.square_error_cost(pred, y)) + 0.01 * aux
paddle.optimizer.Adam(learning_rate=0.01).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())
rng = np.random.RandomState(0)
xs = rng.randn(64, 16).astype(np.float32)
ys = np.tanh(xs.sum(1, keepdims=True) * 0.3).astype(np.float32)
losses = []
for _ in range(40):
    lv, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
    losses.append(float(lv))
print(json.dumps({"first": losses[0], "last": losses[-1]}))
""")], env=cpu_mesh_env(8), capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["last"] < res["first"] * 0.7


def test_ep_sharded_matches_unsharded():
    """ep=4 expert-sharded run must produce the same losses as unsharded —
    GSPMD all-to-all dispatch is numerics-preserving."""
    code = textwrap.dedent("""
import json
import numpy as np
import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel.mesh import moe_sharding_rules

def run(ep):
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=3)
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h, aux = layers.switch_moe(x, num_experts=4, d_ff=32)
    pred = layers.fc(h, 1)
    loss = layers.mean(layers.square_error_cost(pred, y)) + 0.01 * aux
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.expert_parallel_degree = ep
    if ep > 1:
        s.tensor_parallel_rules = moe_sharding_rules()
    opt = fleet.distributed_optimizer(paddle.optimizer.SGD(0.05), s)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.tanh(xs.sum(1, keepdims=True) * 0.3).astype(np.float32)
    return [float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
            for _ in range(6)]

plain = run(1)
sharded = run(4)
print(json.dumps({"plain": plain, "sharded": sharded}))
""")
    out = subprocess.run([sys.executable, "-c", code], env=cpu_mesh_env(8),
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr
    res = json.loads(out.stdout.strip().splitlines()[-1])
    np.testing.assert_allclose(res["sharded"], res["plain"],
                               rtol=2e-4, atol=2e-5)
    assert res["plain"][-1] < res["plain"][0]


def test_manual_dp_declines_moe_cross_batch():
    """switch_moe couples tokens ACROSS the batch (FCFS expert capacity +
    the aux balancing loss average over the token axis), so the bucketed
    manual-dp shard_map path must decline MoE programs — a per-shard run
    silently computes LOCAL routing statistics, which was exactly the
    standing ep-parity failure above (the ep=1 arm resolved to a dp-pure
    mesh and took the manual path). Build-only regression guard; the
    numeric contract is test_ep_sharded_matches_unsharded."""
    from paddle_tpu.parallel.zero import _cross_batch_ops, _iter_op_types
    cross_batch = _cross_batch_ops()   # one table: analysis/op_specs.py
    assert "switch_moe" in cross_batch

    # the detection must see through fused sub-graph bodies too: after
    # recompute the switch_moe op lives inside a __segment__'s sub_ops
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.parallel.transforms import apply_recompute
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    h, aux = layers.switch_moe(x, num_experts=2, d_ff=8)
    out = layers.mean(layers.fc(h, 1))
    # one multi-op segment ending at the loss: switch_moe fuses inside it
    apply_recompute(fluid.default_main_program(), [out.name])
    prog = fluid.default_main_program()
    gb = prog.global_block()
    assert not any(op.type == "switch_moe" for op in gb.ops), \
        "recompute should have fused switch_moe into a __segment__"
    assert any(t in cross_batch for t in _iter_op_types(prog))


def test_top2_matches_dense_reference():
    """GShard top-2 with ample capacity == sum of the two best experts'
    FFNs weighted by pair-renormalized gates."""
    n, d, e, ff = 6, 4, 3, 8
    ins = _moe_ins(n=n, d=d, e=e, ff=ff)
    out = np.asarray(run_op("switch_moe", ins,
                            {"capacity_factor": float(n), "top_k": 2}
                            )["Out"][0])
    x = ins["X"][0]
    logits = x @ ins["GateW"][0]
    gates = np.exp(logits - logits.max(-1, keepdims=True))
    gates /= gates.sum(-1, keepdims=True)
    ref = np.zeros_like(x)
    for i in range(n):
        top2 = np.argsort(-gates[i])[:2]
        g = gates[i, top2]
        g = g / g.sum()
        for k, ex in enumerate(top2):
            h = np.maximum(x[i] @ ins["ExpertW1"][0][ex], 0)
            ref[i] += g[k] * (h @ ins["ExpertW2"][0][ex])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_unsupported_top_k_raises():
    ins = _moe_ins(n=4, d=4, e=2, ff=8)
    for bad in (0, 3):
        with pytest.raises(Exception, match="top_k"):
            run_op("switch_moe", ins,
                   {"capacity_factor": 4.0, "top_k": bad})


def test_top2_second_choice_queues_behind_firsts():
    """Capacity accounting: second choices only take slots left after ALL
    first choices (GShard order), so with cap == #top1 the second-choice
    dispatch fully drops."""
    n, d, e = 8, 4, 2
    ins = _moe_ins(n=n, d=d, e=e)
    # everyone's top-1 is expert 0 (huge col 0), top-2 is expert 1
    ins["GateW"] = [np.zeros((d, e), np.float32)]
    ins["GateW"][0][:, 0] = 50.0
    ins["X"][0][:] = np.abs(ins["X"][0])
    o1 = np.asarray(run_op("switch_moe", ins,
                           {"capacity_factor": float(n), "top_k": 2}
                           )["Out"][0])
    # cap = n (per expert): expert-1 second choices all fit → every token
    # gets a (tiny) expert-1 contribution too; with cap=n/e they'd differ
    o2 = np.asarray(run_op("switch_moe", ins,
                           {"capacity_factor": 1.0, "top_k": 2})["Out"][0])
    assert not np.allclose(o1, o2), "capacity had no effect on 2nd choices"


def test_capacity_overflow_at_scale():
    """Realistic token count: N=512, E=4, cf=1.0 → cap=128; skewed routing
    overflows and exactly cap tokens per hot expert survive."""
    n, d, e, ff = 512, 8, 4, 16
    ins = _moe_ins(n=n, d=d, e=e, ff=ff)
    ins["GateW"] = [np.zeros((d, e), np.float32)]
    ins["GateW"][0][:, 0] = 10.0          # everyone → expert 0
    ins["X"][0][:] = np.abs(ins["X"][0]) + 0.1
    out = run_op("switch_moe", ins, {"capacity_factor": 1.0})
    o = np.asarray(out["Out"][0])
    nz = (np.abs(o).max(axis=1) > 0).sum()
    assert nz == 128, f"expected exactly cap=128 surviving tokens, got {nz}"


def test_aux_loss_balance_extremes():
    """Uniform routing → aux ≈ 1 (minimum); fully skewed → aux ≈ E."""
    n, d, e = 64, 4, 4
    ins = _moe_ins(n=n, d=d, e=e)
    ins["GateW"] = [np.zeros((d, e), np.float32)]   # uniform gates
    aux_u = float(np.asarray(run_op("switch_moe", ins,
                                    {"capacity_factor": 2.0})["AuxLoss"][0]))
    # ties broken to expert 0: load=[1,0,0,0], importance=1/4 → aux=1? No:
    # aux = E * sum(imp*load) = 4 * 1/4 = 1 for uniform importance. Skew:
    ins["GateW"][0][:, 0] = 20.0
    ins["X"][0][:] = np.abs(ins["X"][0]) + 0.1
    aux_s = float(np.asarray(run_op("switch_moe", ins,
                                    {"capacity_factor": 2.0})["AuxLoss"][0]))
    assert aux_u <= 1.01, aux_u
    assert aux_s > 3.5, aux_s


def test_pretrain_program_adds_aux_loss():
    """build_pretrain_program with moe_experts>0 must fold the aux losses
    into the training loss (VERDICT weak #6): the fetched loss equals
    mlm_mean + 0.01/L * sum(aux)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    cfg = bert.BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position=32, seq_len=16, hidden_dropout=0.0,
                          attention_dropout=0.0, moe_experts=4)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    gb = fluid.default_main_program().global_block()
    aux_names = [op.outputs["AuxLoss"][0] for op in gb.ops
                 if op.type == "switch_moe"]
    assert len(aux_names) == 2, "one aux loss per MoE layer expected"
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"input_ids": rng.randint(0, 128, (4, 16)).astype(np.int64),
            "mlm_labels": rng.randint(0, 128, (4, 16, 1)).astype(np.int64)}
    # the final loss op is elementwise_add(mlm_mean, scaled_aux): fetch its
    # mlm input and check total == mlm + 0.01/L * sum(aux) numerically
    add_op = [op for op in gb.ops if op.type == "elementwise_add"
              and op.outputs["Out"][0] == loss.name][-1]
    mlm_name = add_op.inputs["X"][0]
    fetches = exe.run(feed=feed,
                      fetch_list=[loss, mlm_name] + aux_names)
    total, mlm = float(fetches[0]), float(fetches[1])
    auxes = [float(a) for a in fetches[2:]]
    assert all(a > 0 for a in auxes), auxes
    np.testing.assert_allclose(total, mlm + 0.01 / 2 * sum(auxes),
                               rtol=1e-5)
    assert total > mlm, "aux term numerically invisible"


@pytest.mark.parametrize("top_k", [1, 2])
def test_sorted_dispatch_matches_dense(top_k):
    """The O(E*C*d) sorted scatter/gather path must route every token to
    the SAME expert slot as the dense one-hot einsum formulation — same
    FCFS capacity order, same drops, same top-2 queue-behind-top-1."""
    ins = _moe_ins(n=32, d=4, e=4, ff=8)
    attrs = {"capacity_factor": 0.75, "top_k": top_k}  # forces real drops
    dense = run_op("switch_moe", ins,
                   {**attrs, "dispatch_mode": "dense"})
    sorted_ = run_op("switch_moe", ins,
                     {**attrs, "dispatch_mode": "sorted"})
    np.testing.assert_allclose(np.asarray(dense["Out"][0]),
                               np.asarray(sorted_["Out"][0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dense["AuxLoss"][0]),
                               np.asarray(sorted_["AuxLoss"][0]), rtol=1e-6)
    assert (np.asarray(dense["GateIdx"][0])
            == np.asarray(sorted_["GateIdx"][0])).all()


def test_sorted_dispatch_differentiable():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import registry

    ins = _moe_ins(n=16, d=4, e=2, ff=8)
    opdef = registry.get("switch_moe")

    def loss(mode, x):
        cur = {k: [jnp.asarray(v[0])] for k, v in ins.items()}
        cur["X"] = [x]
        out = opdef.lower(registry.LowerCtx(rng_key=jax.random.PRNGKey(0)),
                          cur, {"capacity_factor": 1.5,
                                "dispatch_mode": mode})
        return jnp.sum(out["Out"][0] ** 2)

    x = jnp.asarray(ins["X"][0])
    g_dense = jax.grad(lambda a: loss("dense", a))(x)
    g_sorted = jax.grad(lambda a: loss("sorted", a))(x)
    assert np.isfinite(np.asarray(g_sorted)).all()
    np.testing.assert_allclose(np.asarray(g_dense), np.asarray(g_sorted),
                               rtol=1e-4, atol=1e-5)
