"""Ring / Ulysses sequence-parallel attention vs dense reference.

New capability (absent in the reference — SURVEY §5 long-context). Runs in
an 8-device CPU mesh subprocess (conftest.cpu_mesh_env), the same
no-cluster pattern as the reference's test_dist_base.py.
"""
import subprocess
import sys
import textwrap

import pytest

from conftest import cpu_mesh_env

# Tier-1 rebalance (ISSUE 16): ~45s of 8-device CPU-mesh subprocesses; the
# parity contract is numeric (vs dense reference) and stable, so it rides
# the ci.py shards (which run the slow tier) rather than the 870s sweep.
pytestmark = pytest.mark.slow


def _run(code, n_devices=8):
    r = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                       env=cpu_mesh_env(n_devices), capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, f"subprocess failed:\n{r.stdout}\n{r.stderr}"
    assert "OK" in r.stdout, r.stdout


def test_ring_attention_matches_dense():
    _run("""
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.parallel import build_mesh, ring_attention

    mesh = build_mesh(dp=2, sp=4)
    b, nh, s, hd = 2, 4, 32, 16
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32))
               for _ in range(3))

    def dense(q, k, v, causal):
        sc = jnp.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
        if causal:
            sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                           sc, -jnp.inf)
        return jnp.einsum("bnqk,bnkd->bnqd", jax.nn.softmax(sc, -1), v)

    for causal in (False, True):
        got = ring_attention(q, k, v, mesh=mesh, causal=causal)
        want = dense(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
    print("OK")
    """)


def test_ring_attention_is_differentiable():
    _run("""
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.parallel import build_mesh, ring_attention

    mesh = build_mesh(dp=2, sp=2)  # 2 hops exercise rotation; sp=4 only
    # inflates compile time (suite-hygiene round 4)
    b, nh, s, hd = 2, 2, 16, 8
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32))
               for _ in range(3))

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, causal=True).sum()

    def loss_dense(q, k, v):
        sc = jnp.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
        sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                       sc, -jnp.inf)
        return jnp.einsum("bnqk,bnkd->bnqd",
                          jax.nn.softmax(sc, -1), v).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-4)
    print("OK")
    """, n_devices=4)


def test_ulysses_matches_dense():
    _run("""
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.parallel import build_mesh, ulysses_attention

    mesh = build_mesh(dp=2, sp=4)
    b, nh, s, hd = 2, 8, 32, 16   # heads divisible by sp=4
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32))
               for _ in range(3))
    got = ulysses_attention(q, k, v, mesh=mesh, causal=True)
    sc = jnp.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd)
    sc = jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None],
                   sc, -jnp.inf)
    want = jnp.einsum("bnqk,bnkd->bnqd", jax.nn.softmax(sc, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    print("OK")
    """)


def test_sequence_parallel_attention_in_program():
    """fused_attention(sequence_parallel=True) inside a jitted program over a
    mesh with an sp axis produces dense-equal outputs."""
    _run("""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.parallel import build_mesh, DistConfig, attach

    b, nh, s, hd = 2, 4, 32, 16
    q = fluid.layers.data(name="q", shape=[nh, s, hd], dtype="float32")
    k = fluid.layers.data(name="k", shape=[nh, s, hd], dtype="float32")
    v = fluid.layers.data(name="v", shape=[nh, s, hd], dtype="float32")
    out_sp = layers.fused_attention(q, k, v, causal=True,
                                    sequence_parallel=True)
    out_ref = layers.fused_attention(q, k, v, causal=True)

    mesh = build_mesh(dp=2, sp=4)
    attach(fluid.default_main_program(), DistConfig(mesh=mesh))
    exe = fluid.Executor()
    rng = np.random.RandomState(3)
    feed = {n: rng.randn(b, nh, s, hd).astype(np.float32)
            for n in ("q", "k", "v")}
    a, b_ = exe.run(feed=feed, fetch_list=[out_sp, out_ref])
    np.testing.assert_allclose(a, b_, rtol=2e-4, atol=2e-5)
    print("OK")
    """)


def test_ring_and_ulysses_key_padding_mask_matches_dense():
    _run("""
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.parallel import build_mesh, ring_attention
    from paddle_tpu.parallel.ring_attention import ulysses_attention

    mesh = build_mesh(dp=2, sp=4)
    b, nh, s, hd = 2, 4, 32, 16
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32))
               for _ in range(3))
    pad = np.zeros((b, 1, 1, s), np.float32)
    pad[0, :, :, 24:] = -1e9
    pad[1, :, :, 28:] = -1e9
    mask = jnp.asarray(pad)

    def dense(q, k, v):
        sc = jnp.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd) + mask
        return jnp.einsum("bnqk,bnkd->bnqd", jax.nn.softmax(sc, -1), v)

    want = dense(q, k, v)
    got_r = ring_attention(q, k, v, mesh=mesh, mask=mask)
    np.testing.assert_allclose(np.asarray(got_r), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    got_u = ulysses_attention(q, k, v, mesh=mesh, mask=mask)
    np.testing.assert_allclose(np.asarray(got_u), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # full [S, S] masks are rejected with a clear message
    try:
        ring_attention(q, k, v, mesh=mesh,
                       mask=jnp.zeros((b, 1, s, s)))
        raise SystemExit("full mask not rejected")
    except ValueError as e:
        assert "KEY-PADDING" in str(e)
    print("OK")
    """)


def test_ring_dropout_semantics_and_determinism():
    _run("""
    import jax, jax.numpy as jnp, numpy as np
    from paddle_tpu.parallel import build_mesh, ring_attention

    mesh = build_mesh(dp=2, sp=2)  # 2 hops: same cross-shard dropout
    # semantics, half the ring-program compile (suite hygiene)
    b, nh, s, hd = 2, 2, 32, 32
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, nh, s, hd).astype(np.float32)) * 0.3
    v_eye = jnp.broadcast_to(jnp.eye(s, dtype=jnp.float32), (b, nh, s, s))

    rate = 0.2
    out = ring_attention(q, k, v_eye, mesh=mesh, dropout=rate, seed=9)
    pd = np.asarray(out)
    probs = np.asarray(jax.nn.softmax(
        jnp.einsum("bnqd,bnkd->bnqk", q, k) / np.sqrt(hd), -1))
    m = pd != 0
    assert abs((1 - m.mean()) - rate) < 0.05, "drop fraction off"
    np.testing.assert_allclose(pd[m] / probs[m], 1 / (1 - rate), rtol=1e-4)
    out2 = ring_attention(q, k, v_eye, mesh=mesh, dropout=rate, seed=9)
    assert np.array_equal(pd, np.asarray(out2)), "same seed must repeat"
    out3 = ring_attention(q, k, v_eye, mesh=mesh, dropout=rate, seed=10)
    assert not np.array_equal(pd, np.asarray(out3))
    # grads flow through the dropped path
    g = jax.grad(lambda a: jnp.sum(ring_attention(
        a, k, v_eye, mesh=mesh, dropout=rate, seed=9)))(q)
    assert np.isfinite(np.asarray(g)).all()
    print("OK")
    """, n_devices=4)


def test_sp_program_trains_with_mask_and_dropout():
    """The BERT sp path no longer silently zeroes attention_dropout and
    accepts the padded-batch input mask (round-4 weak-item fix)."""
    _run("""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import bert
    from paddle_tpu.parallel import build_mesh
    from paddle_tpu.testing import reset_programs

    reset_programs(seed=0)
    mesh = build_mesh(sp=4)
    cfg = bert.BertConfig(vocab_size=256, hidden_size=32, num_layers=1,
                          num_heads=4, intermediate_size=64,
                          max_position=32, seq_len=32,
                          hidden_dropout=0.0, attention_dropout=0.1,
                          sequence_parallel=True)
    ids, labels, loss = bert.build_pretrain_program(cfg,
                                                    use_input_mask=True)
    opt = paddle.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(loss)
    from paddle_tpu.parallel import DistConfig, attach
    attach(fluid.default_main_program(), DistConfig(mesh=mesh))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    B = 4
    lens = rng.randint(16, 33, (B, 1))
    feed = {"input_ids": rng.randint(0, 256, (B, 32)).astype(np.int64),
            "mlm_labels": rng.randint(0, 256, (B, 32, 1)).astype(np.int64),
            "input_mask": (np.arange(32)[None, :] < lens)
            .astype(np.float32)}
    c = [float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
               .reshape(-1)[0]) for _ in range(8)]
    assert np.isfinite(c).all(), c
    assert c[-1] < c[0], c
    print("OK")
    """)
