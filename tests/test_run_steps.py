"""Executor.run_steps: the device-side k-step scan training loop.

Counterpart of running the reference's trainer loop k times; one dispatch
here (see executor.py _run_block_multistep)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.framework import errors


def _build(seed=0):
    np.random.seed(seed)
    x = layers.data(name="x", shape=[6], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, 8, act="tanh")
    pred = layers.fc(h, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    paddle.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


def test_run_steps_matches_sequential_runs():
    rng = np.random.RandomState(0)
    w = rng.randn(6, 1).astype(np.float32)
    xs = rng.randn(5, 16, 6).astype(np.float32)
    ys = np.einsum("kbf,fo->kbo", xs, w).astype(np.float32)

    exe, loss = _build()
    seq_losses = []
    for i in range(5):
        out, = exe.run(feed={"x": xs[i], "y": ys[i]}, fetch_list=[loss])
        seq_losses.append(float(out))
    seq_params = {p.name: np.asarray(fluid.global_scope().find(p.name))
                  for p in fluid.default_main_program().all_parameters()}

    # fresh identical model, one dispatch of 5 steps
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    exe2, loss2 = _build()
    stacked, = exe2.run_steps(5, feed={"x": xs, "y": ys},
                              fetch_list=[loss2])
    np.testing.assert_allclose(stacked.reshape(-1), seq_losses, rtol=2e-4,
                               atol=1e-5)
    for p in fluid.default_main_program().all_parameters():
        np.testing.assert_allclose(
            np.asarray(fluid.global_scope().find(p.name)),
            seq_params[p.name], rtol=2e-4, atol=1e-5)


def test_run_steps_broadcast_feed_and_training_progress():
    exe, loss = _build(seed=1)
    rng = np.random.RandomState(1)
    xb = rng.randn(32, 6).astype(np.float32)
    yb = (xb.sum(1, keepdims=True)).astype(np.float32)
    first, = exe.run_steps(20, feed={"x": xb, "y": yb}, fetch_list=[loss])
    assert first.shape[0] == 20
    assert first[-1] < first[0] * 0.7  # trained across the scanned steps
    # state persisted: a second call continues improving
    second, = exe.run_steps(20, feed={"x": xb, "y": yb}, fetch_list=[loss])
    assert second[-1] < first[-1] * 1.05


def test_run_steps_dropout_varies_per_step():
    np.random.seed(0)
    x = layers.data(name="x", shape=[64], dtype="float32")
    d = layers.dropout(x, dropout_prob=0.5)
    s = layers.reduce_sum(d)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out, = exe.run_steps(4, feed={"x": np.ones((8, 64), np.float32)},
                         fetch_list=[s])
    assert len(set(np.round(np.asarray(out).reshape(-1), 3))) > 1, \
        "each scanned step must draw fresh dropout"


def test_run_steps_k1_matches_run():
    """k=1 is a legal degenerate scan (feeds still carry the [1] axis)."""
    rng = np.random.RandomState(3)
    xb = rng.randn(16, 6).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)

    exe, loss = _build(seed=3)
    ref, = exe.run(feed={"x": xb, "y": yb}, fetch_list=[loss])
    ref_params = {p.name: np.asarray(fluid.global_scope().find(p.name))
                  for p in fluid.default_main_program().all_parameters()}

    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    exe2, loss2 = _build(seed=3)
    stacked, = exe2.run_steps(1, feed={"x": xb, "y": yb},
                              fetch_list=[loss2])
    assert stacked.shape[0] == 1
    np.testing.assert_allclose(stacked[0], ref, rtol=2e-4, atol=1e-5)
    for p in fluid.default_main_program().all_parameters():
        np.testing.assert_allclose(
            np.asarray(fluid.global_scope().find(p.name)),
            ref_params[p.name], rtol=2e-4, atol=1e-5)


def test_run_steps_rejects_k_below_one():
    exe, loss = _build(seed=4)
    with pytest.raises(errors.InvalidArgumentError):
        exe.run_steps(0, feed={}, fetch_list=[loss])


def test_run_steps_rejects_ps_and_pipeline():
    exe, loss = _build(seed=2)
    prog = fluid.default_main_program()
    prog._ps_hooks = [object()]
    with pytest.raises(errors.UnimplementedError):
        exe.run_steps(2, feed={}, fetch_list=[loss])
    prog._ps_hooks = []
    prog._microbatch_k = 4
    with pytest.raises(errors.UnimplementedError):
        exe.run_steps(2, feed={}, fetch_list=[loss])
    prog._microbatch_k = 0
