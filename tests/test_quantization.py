"""Quantization (reference contrib/slim/quantization): fake-quant op
numerics + STE grads, QAT transform training, PTQ calibration."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from op_test import run_op

R = np.random.RandomState(0)


def test_fake_qdq_numerics_and_ste_grad():
    x = R.randn(4, 6).astype(np.float32)
    out = run_op("fake_quantize_dequantize_abs_max", {"X": [x]},
                 {"bit_length": 8})
    o = np.asarray(out["Out"][0])
    scale = float(np.asarray(out["OutScale"][0]).reshape(-1)[0])
    assert abs(scale - np.abs(x).max()) < 1e-6
    q = np.clip(np.round(x / scale * 127), -127, 127)
    np.testing.assert_allclose(o, q * scale / 127, rtol=1e-5, atol=1e-6)
    # quantization error bounded by half a step
    assert np.abs(o - x).max() <= scale / 127
    # STE: gradient of sum(out) wrt x is exactly ones (NOT the true
    # staircase derivative — that's the point of the straight-through
    # estimator, so no finite-difference check here)
    import jax
    import jax.numpy as jnp

    def f(xx):
        return jnp.sum(run_op("fake_quantize_dequantize_abs_max",
                              {"X": [xx]}, {"bit_length": 8})["Out"][0])

    g = np.asarray(jax.grad(f)(jnp.asarray(x)))
    np.testing.assert_allclose(g, np.ones_like(x), rtol=1e-6)


def test_channel_wise_scales():
    w = R.randn(5, 3).astype(np.float32) * np.array([1., 10., 100.])
    out = run_op("fake_channel_wise_quantize_dequantize_abs_max",
                 {"X": [w]}, {"bit_length": 8, "quant_axis": 1})
    scales = np.asarray(out["OutScale"][0])
    np.testing.assert_allclose(scales, np.abs(w).max(axis=0), rtol=1e-6)


def test_qat_transform_trains_and_stays_close():
    def build(quant):
        from paddle_tpu.testing import reset_programs
        reset_programs(seed=4)
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, 16, act="relu",
                      param_attr=paddle.ParamAttr(name="w1"))
        pred = layers.fc(h, 1, param_attr=paddle.ParamAttr(name="w2"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        if quant:
            from paddle_tpu.contrib.slim import QuantizationTransformPass
            QuantizationTransformPass().apply(main, startup)
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(1)
        xs = rng.randn(32, 8).astype(np.float32)
        ys = (xs.sum(1, keepdims=True) * 0.2).astype(np.float32)
        losses = [float(exe.run(main, feed={"x": xs, "y": ys},
                                fetch_list=[loss])[0]) for _ in range(25)]
        return losses, main

    fl, _ = build(False)
    ql, qprog = build(True)
    ops = [op.type for op in qprog.global_block().ops]
    assert "fake_channel_wise_quantize_dequantize_abs_max" in ops
    assert "fake_quantize_dequantize_moving_average_abs_max" in ops
    assert ql[-1] < ql[0] * 0.5                      # QAT trains
    assert abs(ql[-1] - fl[-1]) < max(0.1, fl[-1])   # close to float


def test_ptq_calibration():
    from paddle_tpu.contrib.slim import PostTrainingQuantization
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=5)
    x = layers.data(name="x", shape=[6], dtype="float32")
    h = layers.fc(x, 8, act="relu", param_attr=paddle.ParamAttr(name="pw"))
    out = layers.fc(h, 2, param_attr=paddle.ParamAttr(name="pw2"))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    feeds = [{"x": rng.randn(16, 6).astype(np.float32)} for _ in range(3)]
    float_out = np.asarray(exe.run(feed=feeds[0], fetch_list=[out])[0])

    ptq = PostTrainingQuantization(exe, fluid.default_main_program(),
                                   ["x"], [out], feeds)
    qprog = ptq.quantize()
    ops = [op.type for op in qprog.global_block().ops]
    assert "fake_quantize_dequantize_abs_max" in ops
    q_out = np.asarray(exe.run(qprog, feed=feeds[0], fetch_list=[out])[0])
    # int8 emulation stays close to the float program
    denom = np.abs(float_out).max()
    assert np.abs(q_out - float_out).max() / denom < 0.05
