"""KV-cache decode parity: the scan/cached generation loop
(models/gpt_decode.py) must emit exactly the tokens a full causal forward
through the static-graph executor emits (the reference has no in-tree
autoregressive loop — its predictor re-runs full forwards; cached decode
must be indistinguishable from that)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import layers
from paddle_tpu.models.gpt import GPTConfig, gpt_decoder
from paddle_tpu.models import gpt_decode

PROMPT, NEW = 8, 6


def _build(total_len):
    cfg = GPTConfig.tiny()
    cfg.seq_len = total_len
    cfg.max_position = 64
    tokens = layers.data(name="tokens", shape=[cfg.seq_len], dtype="int64")
    seq, wte = gpt_decoder(tokens, cfg)
    logits = layers.matmul(seq, wte, transpose_y=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return cfg, exe, tokens, logits


def _naive_generate(exe, logits, prompt, new_tokens, total_len):
    """Full-recompute argmax decoding through the executor: position t's
    logits only see tokens <= t (causal), so junk padding is harmless."""
    b = prompt.shape[0]
    toks = np.zeros((b, total_len), np.int64)
    toks[:, :prompt.shape[1]] = prompt
    cur = prompt.shape[1]
    for _ in range(new_tokens):
        lg = exe.run(feed={"tokens": toks},
                     fetch_list=[logits])[0]
        toks[:, cur] = np.argmax(lg[:, cur - 1], axis=-1)
        cur += 1
    return toks


def test_cached_decode_matches_full_recompute():
    total = PROMPT + NEW
    cfg, exe, _, logits = _build(total)
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, cfg.vocab_size, (2, PROMPT)).astype(np.int64)

    expect = _naive_generate(exe, logits, prompt, NEW, total)
    params = gpt_decode.params_from_scope(cfg)
    got = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW))
    assert got.shape == (2, total)
    np.testing.assert_array_equal(got, expect)


def test_sampled_decode_deterministic_and_in_range():
    total = PROMPT + NEW
    cfg, exe, _, _ = _build(total)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, cfg.vocab_size, (2, PROMPT)).astype(np.int64)
    params = gpt_decode.params_from_scope(cfg)
    a = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW,
                                       temperature=0.8, top_k=16, seed=11))
    b = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW,
                                       temperature=0.8, top_k=16, seed=11))
    c = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW,
                                       temperature=0.8, top_k=16, seed=12))
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < cfg.vocab_size
    assert not np.array_equal(a, c)  # different seed explores


def test_eos_latches():
    total = PROMPT + NEW
    cfg, exe, _, logits = _build(total)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, cfg.vocab_size, (1, PROMPT)).astype(np.int64)
    params = gpt_decode.params_from_scope(cfg)
    greedy = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW))
    eos = int(greedy[0, PROMPT + 1])  # force the 2nd generated token as eos
    out = np.asarray(gpt_decode.generate(params, cfg, prompt, NEW,
                                         eos_token=eos))
    tail = out[0, PROMPT:]
    hit = np.where(tail == eos)[0]
    assert hit.size, "eos never emitted despite matching the greedy path"
    # every position after the first eos is eos (latched)
    assert (tail[hit[0]:] == eos).all()


def test_padded_prefill_resumes_at_prompt_len():
    """prefill's padded-prompt contract: with prompt_len < Sp, decoding
    from pos = prompt_len (pad slots overwritten in order) must emit the
    same tokens as an unpadded prefill of just the real prompt."""
    import jax.numpy as jnp

    total = PROMPT + NEW
    cfg, exe, _, _ = _build(total)
    rng = np.random.RandomState(9)
    prompt = rng.randint(0, cfg.vocab_size, (2, PROMPT)).astype(np.int64)
    params = gpt_decode.params_from_scope(cfg)
    max_len = PROMPT + NEW

    def run(padded, prompt_len):
        ck, cv, logits = gpt_decode.prefill(
            params, cfg, jnp.asarray(padded, jnp.int32),
            jnp.int32(prompt_len), max_len)
        toks = [np.asarray(jnp.argmax(logits, -1))]
        pos = prompt_len
        for _ in range(NEW - 1):
            ck, cv, logits = gpt_decode.decode_step(
                params, cfg, ck, cv, jnp.asarray(toks[-1], jnp.int32),
                jnp.int32(pos))
            toks.append(np.asarray(jnp.argmax(logits, -1)))
            pos += 1
        return np.stack(toks, 1)

    clean = run(prompt, PROMPT)
    # pad with junk tokens beyond prompt_len; same real prefix
    padded = np.concatenate(
        [prompt, rng.randint(0, cfg.vocab_size, (2, 3))], axis=1)
    np.testing.assert_array_equal(run(padded, PROMPT), clean)


def test_max_position_guard():
    cfg = GPTConfig.tiny()
    params = {}
    with pytest.raises(ValueError, match="max_position"):
        gpt_decode.generate(params, cfg, np.zeros((1, 60), np.int64), 10)


def test_bf16_params_decode_precision_and_validity():
    """Serving-dtype path: params_from_scope(dtype='bfloat16') halves the
    weight bytes each generated token reads. Precision is asserted where
    it is measurable without decode-chain divergence effects: the
    prefill logits of the bf16 path must track the f32 path within bf16
    rounding tolerance (LN params stay f32, LN/score/head matmuls
    accumulate f32). The generate() output is checked for shape/range
    validity only — token-level agreement is chaotic by construction
    (one near-tie flip changes every later position's context)."""
    import jax.numpy as jnp

    total = PROMPT + NEW
    cfg, exe, _, logits = _build(total)
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, cfg.vocab_size, (2, PROMPT)).astype(np.int64)

    p32 = gpt_decode.params_from_scope(cfg)
    p16 = gpt_decode.params_from_scope(cfg, dtype="bfloat16")
    assert p16["wte"].dtype == jnp.bfloat16
    assert p16["final_ln_scale"].dtype == jnp.float32   # LN excluded
    _, _, lg32 = gpt_decode.prefill(p32, cfg, jnp.asarray(prompt),
                                    jnp.int32(PROMPT), total)
    _, _, lg16 = gpt_decode.prefill(p16, cfg, jnp.asarray(prompt),
                                    jnp.int32(PROMPT), total)
    np.testing.assert_allclose(np.asarray(lg16), np.asarray(lg32),
                               rtol=0.05, atol=0.05)
    got16 = np.asarray(gpt_decode.generate(p16, cfg, prompt, NEW))
    assert got16.shape == (2, total)
    assert ((0 <= got16) & (got16 < cfg.vocab_size)).all()
