"""Sparse PS: native KV service (TCP loopback) + distributed embedding.

Mirrors reference tests rpc_server_test.cc / collective_server_test.cc
(in-process client+server loopback — multi-node RPC tested without a
cluster) and the fleet PS CTR tests (dist_fleet_ctr.py) at toy scale.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.distributed.ps import (KVClient, KVServer, SparseTableConfig,
                                       distributed_embedding)


@pytest.fixture(autouse=True)
def fresh_programs():
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)
    yield


@pytest.fixture()
def server():
    srv = KVServer([SparseTableConfig("emb", dim=4, init_scale=0.1),
                    SparseTableConfig("wide", dim=1, init_scale=0.0)])
    port = srv.start(0)
    yield srv, port
    srv.stop()


def test_pull_push_roundtrip(server):
    srv, port = server
    c = KVClient("127.0.0.1", port)
    keys = np.array([3, 99, 7, 3], np.int64)
    rows = c.pull(0, keys, 4)
    assert rows.shape == (4, 4)
    # deterministic lazy init: same key pulls identical rows
    np.testing.assert_allclose(rows[0], rows[3])
    assert np.abs(rows).max() <= 0.1 + 1e-6

    g = np.ones((4, 4), np.float32)
    c.push(0, keys, g, lr=0.5)
    rows2 = c.pull(0, keys, 4)
    # key 3 appears twice in the push: w -= 0.5*1 applied twice
    np.testing.assert_allclose(rows2[0], rows[0] - 1.0, rtol=1e-5)
    np.testing.assert_allclose(rows2[1], rows[1] - 0.5, rtol=1e-5)
    assert c.table_size(0) == 3
    c.close()


def test_async_client_merges_and_flushes(server):
    srv, port = server
    c = KVClient("127.0.0.1", port, a_sync=True, flush_ms=10)
    base = c.pull(0, np.array([42], np.int64), 4)
    for _ in range(5):
        c.push(0, np.array([42], np.int64), np.ones((1, 4), np.float32),
               lr=0.1)
    c.flush()
    time.sleep(0.05)
    got = c.pull(0, np.array([42], np.int64), 4)
    np.testing.assert_allclose(got, base - 0.5, rtol=1e-4)  # 5 merged pushes
    c.close()


def test_heartbeat_lost_worker_detection(server):
    srv, port = server
    c = KVClient("127.0.0.1", port, worker_id=7)
    assert c.ping()
    time.sleep(0.05)
    lost = srv.lost_workers(timeout_s=0.01)
    assert lost == [7]
    assert srv.lost_workers(timeout_s=60.0) == []
    c.close()


def test_save_load_roundtrip(server, tmp_path):
    srv, port = server
    c = KVClient("127.0.0.1", port)
    keys = np.arange(10, dtype=np.int64)
    c.push(0, keys, np.ones((10, 4), np.float32), lr=1.0)
    want = c.pull(0, keys, 4)
    path = str(tmp_path / "table0.bin")
    c.save(0, path)

    srv2 = KVServer([SparseTableConfig("emb", dim=4, init_scale=0.1)])
    p2 = srv2.start(0)
    c2 = KVClient("127.0.0.1", p2)
    c2.load(0, path)
    got = c2.pull(0, keys, 4)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    c.close()
    c2.close()
    srv2.stop()


def test_distributed_embedding_end_to_end(server):
    """CTR-style model: sparse rows live on the pserver, dense math on
    device; loss must drop and the server's table must move."""
    from paddle_tpu.distributed import fleet
    srv, port = server

    ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
    dense = fluid.layers.data(name="dense", shape=[5], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    emb = distributed_embedding(ids, "emb", dim=4, lr=0.5)
    feat = layers.concat([layers.reshape(emb, [-1, 12]), dense], axis=1)
    pred = layers.fc(feat, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))

    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        server_endpoints=[f"127.0.0.1:{port}"]))
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1), fleet.DistributedStrategy())
    opt.minimize(loss)
    client = fleet.init_worker()

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 50, (16, 3)).astype(np.int64)
    dense_np = rng.randn(16, 5).astype(np.float32)
    y_np = (dense_np.sum(1, keepdims=True) * 0.3).astype(np.float32)
    before = client.pull(0, np.unique(ids_np), 4)
    losses = []
    for _ in range(30):
        lv, = exe.run(feed={"ids": ids_np, "dense": dense_np, "y": y_np},
                      fetch_list=[loss])
        losses.append(float(lv))
    after = client.pull(0, np.unique(ids_np), 4)
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert np.abs(after - before).max() > 1e-4  # server table trained
    fleet.stop_worker()


def test_server_side_adam_optimizer():
    """Pluggable server optimizers (reference pservers run optimizer blocks,
    listen_and_serv_op.cc:127): adam row states live server-side."""
    srv = KVServer([SparseTableConfig("t", dim=4, init_scale=0.0,
                                      optimizer="adam")])
    port = srv.start(0)
    try:
        c = KVClient("127.0.0.1", port)
        keys = np.array([3], np.int64)
        g = np.full((1, 4), 0.5, np.float32)
        c.push(0, keys, g, lr=0.1)
        w1 = c.pull(0, keys, 4)
        # adam step 1 from zero state: m=0.05..., update = lr * g/|g| ≈ lr
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = (1 - b1) * 0.5
        v = (1 - b2) * 0.25
        lr_t = 0.1 * np.sqrt(1 - b2) / (1 - b1)
        expect = -lr_t * m / (np.sqrt(v) + eps)
        np.testing.assert_allclose(w1[0], expect, rtol=1e-4)
        c.push(0, keys, g, lr=0.1)
        w2 = c.pull(0, keys, 4)
        assert (w2 < w1).all()   # second step keeps moving
        c.close()
    finally:
        srv.stop()


def test_server_side_adagrad_optimizer():
    srv = KVServer([SparseTableConfig("t", dim=2, init_scale=0.0,
                                      optimizer="adagrad")])
    port = srv.start(0)
    try:
        c = KVClient("127.0.0.1", port)
        keys = np.array([1], np.int64)
        g = np.array([[1.0, 2.0]], np.float32)
        c.push(0, keys, g, lr=0.5)
        w = c.pull(0, keys, 2)
        # adagrad: G=g^2; w -= lr*g/(sqrt(G)+eps) = -lr*sign(g)
        np.testing.assert_allclose(w[0], [-0.5, -0.5], rtol=1e-4)
        c.close()
    finally:
        srv.stop()


def test_geo_push_delta_merges_two_workers():
    """Geo protocol op: two workers' deltas accumulate additively
    (communicator.h:413 Geo semantics)."""
    srv = KVServer([SparseTableConfig("t", dim=2, init_scale=0.0)])
    port = srv.start(0)
    try:
        c1 = KVClient("127.0.0.1", port, worker_id=0)
        c2 = KVClient("127.0.0.1", port, worker_id=1)
        keys = np.array([7], np.int64)
        c1.push_delta(0, keys, np.array([[1.0, 2.0]], np.float32))
        c2.push_delta(0, keys, np.array([[10.0, 20.0]], np.float32))
        w = c1.pull(0, keys, 2)
        np.testing.assert_allclose(w[0], [11.0, 22.0], rtol=1e-5)
        c1.close(); c2.close()
    finally:
        srv.stop()


def test_geo_hook_end_to_end(server):
    """distributed_embedding in geo mode: server rows move only at k-step
    syncs, training converges, and the final server state reflects the
    locally-trained deltas."""
    from paddle_tpu.distributed import fleet
    srv, port = server

    ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    emb = distributed_embedding(ids, "emb", dim=4, lr=0.2)
    pred = fluid.layers.fc(layers.reshape(emb, [-1, 12]), size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))

    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        server_endpoints=[f"127.0.0.1:{port}"]))
    strategy = fleet.DistributedStrategy()
    strategy.a_sync = True
    strategy.a_sync_configs = {"k_steps": 4}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1), strategy)
    opt.minimize(loss)
    client = fleet.init_worker()
    hooks = fluid.default_main_program()._ps_hooks
    assert hooks[0].geo_k == 4

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 30, (16, 3)).astype(np.int64)
    y_np = rng.randn(16, 1).astype(np.float32)
    uniq = np.unique(ids_np)
    before = client.pull(0, uniq, 4)
    losses = []
    for step in range(3):   # steps 1..3: no sync yet
        lv, = exe.run(feed={"ids": ids_np, "y": y_np}, fetch_list=[loss])
        losses.append(float(lv))
    mid = client.pull(0, uniq, 4)
    np.testing.assert_allclose(mid, before, rtol=1e-6)  # server untouched
    lv, = exe.run(feed={"ids": ids_np, "y": y_np}, fetch_list=[loss])
    losses.append(float(lv))
    after = client.pull(0, uniq, 4)   # 4th step triggered the delta push
    assert np.abs(after - before).max() > 1e-4
    for _ in range(16):
        lv, = exe.run(feed={"ids": ids_np, "y": y_np}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses[::5]


def test_save_load_preserves_optimizer_state(tmp_path):
    """Checkpoint round trip must carry the adam row states, not just
    weights — else the restored server restarts adam from t=1."""
    srv = KVServer([SparseTableConfig("t", dim=2, init_scale=0.0,
                                      optimizer="adam")])
    port = srv.start(0)
    c = KVClient("127.0.0.1", port)
    keys = np.array([5], np.int64)
    g = np.array([[1.0, 1.0]], np.float32)
    for _ in range(3):
        c.push(0, keys, g, lr=0.1)
    w3 = c.pull(0, keys, 2)
    path = str(tmp_path / "adam_table.bin")
    c.save(0, path)

    srv2 = KVServer([SparseTableConfig("t", dim=2, init_scale=0.0,
                                      optimizer="adam")])
    p2 = srv2.start(0)
    c2 = KVClient("127.0.0.1", p2)
    c2.load(0, path)
    # 4th push on the restored server == 4th push on the original
    c.push(0, keys, g, lr=0.1)
    c2.push(0, keys, g, lr=0.1)
    np.testing.assert_allclose(c2.pull(0, keys, 2), c.pull(0, keys, 2),
                               rtol=1e-6)
    c.close(); c2.close(); srv.stop(); srv2.stop()


def _train_ps_mode(k_steps, steps=24, seed=3):
    """Train a tiny embedding regression against a fresh KV server in sync
    (k_steps=0 → a_sync off) or geo (k_steps>0) mode; return the losses."""
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    from paddle_tpu.distributed import fleet
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)

    srv = KVServer([SparseTableConfig("geo_emb", dim=4, init_scale=0.1)])
    port = srv.start(0)
    try:
        ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        emb = distributed_embedding(ids, "geo_emb", dim=4, lr=0.2)
        pred = fluid.layers.fc(layers.reshape(emb, [-1, 12]), size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))

        fleet.init(role_maker=fleet.UserDefinedRoleMaker(
            server_endpoints=[f"127.0.0.1:{port}"]))
        strategy = fleet.DistributedStrategy()
        if k_steps:
            strategy.a_sync = True
            strategy.a_sync_configs = {"k_steps": k_steps}
        opt = fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1), strategy)
        opt.minimize(loss)
        fleet.init_worker()

        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(seed)
        idv = rng.randint(0, 50, (16, 3)).astype(np.int64)
        yv = rng.randn(16, 1).astype(np.float32)
        losses = []
        for _ in range(steps):
            lv, = exe.run(feed={"ids": idv, "y": yv}, fetch_list=[loss])
            losses.append(float(lv))
        return losses
    finally:
        srv.stop()


def test_geo_sgd_convergence_parity_vs_sync():
    """Reference bar (test_dist_base.py loss-delta asserts): geo-SGD with
    k-step delta sync must track sync PS training — same data, same seeds,
    final loss within tolerance and both strictly converging."""
    sync = _train_ps_mode(0)
    geo = _train_ps_mode(4)
    assert sync[-1] < sync[0] * 0.5, f"sync did not converge: {sync}"
    assert geo[-1] < geo[0] * 0.5, f"geo did not converge: {geo}"
    # single-worker geo applies the same local updates, synced every k
    # steps — final losses must agree within a small delta
    assert abs(geo[-1] - sync[-1]) <= max(0.25 * sync[-1], 0.05), \
        f"geo={geo[-1]:.4f} vs sync={sync[-1]:.4f}"


def test_hot_row_cache_hits_and_parity(server):
    """Cache tier (box_ps re-imagining): read-mostly pulls hit the cache;
    pushes invalidate so a 1-worker cached client is EXACT vs uncached."""
    from paddle_tpu.distributed.ps import ShardedKVClient
    srv, port = server
    cached = ShardedKVClient([f"127.0.0.1:{port}"], cache_rows=1000)
    plain = ShardedKVClient([f"127.0.0.1:{port}"], cache_rows=0)
    keys = np.arange(10, dtype=np.int64)
    a = cached.pull(0, keys, 4)
    np.testing.assert_allclose(a, plain.pull(0, keys, 4))
    # read-mostly: repeat pulls are all hits
    for _ in range(5):
        b = cached.pull(0, keys, 4)
        np.testing.assert_allclose(b, a)
    assert cached.cache.hit_rate > 0.7, cached.cache.hit_rate
    # push invalidates: the next pull sees the server-side SGD update
    g = np.ones((3, 4), np.float32)
    cached.push(0, keys[:3], g, lr=0.5)
    after = cached.pull(0, keys, 4)
    np.testing.assert_allclose(after[:3], a[:3] - 0.5 * g, atol=1e-6)
    np.testing.assert_allclose(after[3:], a[3:])
    np.testing.assert_allclose(after, plain.pull(0, keys, 4))


def test_hot_row_cache_staleness_bound(server):
    """Another worker's push becomes visible within max_stale_pulls."""
    from paddle_tpu.distributed.ps import ShardedKVClient
    srv, port = server
    reader = ShardedKVClient([f"127.0.0.1:{port}"], cache_rows=100,
                             cache_max_stale=3)
    writer = ShardedKVClient([f"127.0.0.1:{port}"], cache_rows=0,
                             worker_id=1)
    keys = np.array([42], np.int64)
    v0 = reader.pull(0, keys, 4).copy()
    writer.push(0, keys, np.ones((1, 4), np.float32), lr=1.0)
    fresh = writer.pull(0, keys, 4)
    assert not np.allclose(fresh, v0)
    seen = [reader.pull(0, keys, 4).copy() for _ in range(5)]
    assert np.allclose(seen[0], v0)          # still cached
    np.testing.assert_allclose(seen[-1], fresh)  # expired within bound
    # LRU eviction respects capacity
    small = ShardedKVClient([f"127.0.0.1:{port}"], cache_rows=4)
    small.pull(0, np.arange(10, dtype=np.int64), 4)
    assert len(small.cache._rows) <= 4


def test_fleet_strategy_sparse_cache_rows(server):
    """strategy.sparse_cache_rows wires the HotRowCache into the fleet
    worker client."""
    from paddle_tpu.distributed import fleet
    srv, port = server
    st = fleet.DistributedStrategy()
    st.sparse_cache_rows = 64
    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        server_endpoints=[f"127.0.0.1:{port}"]), strategy=st)
    fleet.init_worker()
    client = fleet.fleet._kv_client
    assert client.cache is not None and client.cache.capacity == 64
    keys = np.arange(8, dtype=np.int64)
    a = client.pull(0, keys, 4)
    b = client.pull(0, keys, 4)
    np.testing.assert_allclose(a, b)
    assert client.cache.hits >= 8


def test_run_steps_ps_window_pull_once_push_summed(server):
    """k-step PS window (Executor.run_steps + _PsHook.pre_multi/post_multi,
    the reference async-communicator batching): one pull covers all k
    batches' ids, rows stay frozen within the window, and the summed grads
    land in ONE push — server row delta == lr_table * sum_k(grad_k)."""
    from paddle_tpu.distributed import fleet
    srv, port = server

    ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
    emb = distributed_embedding(ids, "emb", dim=4, lr=0.5)
    # loss = mean(emb): d loss / d pulled row r = multiplicity(r)/numel —
    # independent of row VALUES, so the frozen-window semantics are exact
    # and the expected push is analytic
    loss = layers.reduce_mean(emb)
    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        server_endpoints=[f"127.0.0.1:{port}"]))
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1), fleet.DistributedStrategy())
    opt.minimize(loss)
    client = fleet.init_worker()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    k, b = 4, 8
    rng = np.random.RandomState(7)
    ids_np = rng.randint(0, 30, (k, b, 3)).astype(np.int64)
    uniq = np.unique(ids_np)
    before = client.pull(0, uniq, 4)
    out, = exe.run_steps(k, feed={"ids": ids_np}, fetch_list=[loss])
    assert out.shape == (k,)
    after = client.pull(0, uniq, 4)

    counts = np.zeros(len(uniq))
    for kk in range(k):
        u, c = np.unique(ids_np[kk], return_counts=True)
        counts[np.searchsorted(uniq, u)] += c / ids_np[kk].size
    # server SGD rule: row -= table_lr * summed_grad; grad rows broadcast
    # the per-row scalar across dim
    expect = before - 0.5 * counts[:, None] / 4.0
    np.testing.assert_allclose(after, expect, rtol=1e-5, atol=1e-6)
    fleet.stop_worker()


def test_run_steps_ps_window_trains_wide_deep(server):
    """The CTR model trains through windows: loss decreases across k-step
    dispatches and the server table moves."""
    from paddle_tpu.distributed import fleet
    srv, port = server

    ids = fluid.layers.data(name="ids", shape=[3], dtype="int64")
    dense = fluid.layers.data(name="dense", shape=[5], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    emb = distributed_embedding(ids, "emb", dim=4, lr=0.5)
    feat = layers.concat([layers.reshape(emb, [-1, 12]), dense], axis=1)
    pred = layers.fc(feat, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fleet.init(role_maker=fleet.UserDefinedRoleMaker(
        server_endpoints=[f"127.0.0.1:{port}"]))
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1), fleet.DistributedStrategy())
    opt.minimize(loss)
    client = fleet.init_worker()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    k, b = 4, 16
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 50, (k, b, 3)).astype(np.int64)
    dense_np = rng.randn(k, b, 5).astype(np.float32)
    y_np = (dense_np.sum(2, keepdims=True) * 0.3).astype(np.float32)
    before = client.pull(0, np.unique(ids_np), 4)
    first = last = None
    for w in range(8):
        out, = exe.run_steps(k, feed={"ids": ids_np, "dense": dense_np,
                                      "y": y_np}, fetch_list=[loss])
        if w == 0:
            first = float(np.asarray(out)[0])
        last = float(np.asarray(out)[-1])
    after = client.pull(0, np.unique(ids_np), 4)
    assert last < first * 0.5, (first, last)
    assert np.abs(after - before).max() > 1e-4
    fleet.stop_worker()
