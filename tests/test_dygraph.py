"""Dygraph engine tests: eager ops, tape autograd, nn.Layer stack, optimizers.

Modeled on reference tests: unittests/test_imperative_basic.py,
test_imperative_mnist.py, dygraph/static parity checks.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


@pytest.fixture(autouse=True)
def _dygraph_mode():
    paddle.disable_static()
    yield
    paddle.enable_static()


def test_eager_arithmetic_and_numpy():
    a = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    b = paddle.to_tensor([[10.0, 20.0], [30.0, 40.0]])
    c = a + b * 2
    np.testing.assert_allclose(c.numpy(), [[21, 42], [63, 84]])
    assert (a @ b).shape == (2, 2)
    assert float(paddle.mean(a)) == 2.5


def test_backward_simple():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = paddle.sum(x * x)          # y = x^2, dy/dx = 2x
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [4.0, 6.0])


def test_backward_chain_and_accumulation():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    a = x * 3.0
    b = a + x          # b = 4x ; db/dx = 4
    loss = paddle.sum(b * b)  # d/dx = 2*4x*4 = 32x
    loss.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [32.0, 64.0])


def test_no_grad_blocks_tape():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 5.0
    assert y.stop_gradient
    z = x * 2.0
    paddle.sum(z).backward()
    np.testing.assert_allclose(np.asarray(x.grad), [2.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(np.asarray(gx.value), [27.0])


def test_linear_layer_and_state_dict():
    layer = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    out = layer(x)
    assert out.shape == (3, 2)
    sd = layer.state_dict()
    assert set(sd) == {"weight", "bias"}
    layer2 = nn.Linear(4, 2)
    layer2.set_state_dict(sd)
    np.testing.assert_allclose(layer2(x).numpy(), out.numpy())


def test_mlp_trains_with_adam():
    paddle.dygraph.current_tracer().seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameter_list=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(32, 8).astype(np.float32))
    y = paddle.to_tensor(rng.rand(32, 1).astype(np.float32))
    losses = []
    for _ in range(40):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0]


def test_conv_bn_dropout_net():
    model = nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm2D(4), nn.ReLU(),
        nn.MaxPool2D(2), nn.Flatten(), nn.Dropout(0.2), nn.Linear(4 * 4 * 4, 3))
    x = paddle.to_tensor(np.random.rand(2, 1, 8, 8).astype(np.float32))
    out = model(x)
    assert out.shape == (2, 3)
    label = paddle.to_tensor(np.array([[0], [2]], np.int64))
    loss = F.cross_entropy(out, label)
    loss.backward()
    grads = [p.grad for p in model.parameters() if p.trainable]
    assert all(g is not None for g in grads)
    # eval mode: dropout off, BN uses running stats
    model.eval()
    out1 = model(x)
    out2 = model(x)
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-6)


def test_batch_norm_updates_running_stats():
    bn = nn.BatchNorm2D(2, momentum=0.5)
    before = bn._mean.numpy().copy()
    x = paddle.to_tensor(np.random.rand(4, 2, 3, 3).astype(np.float32) + 5.0)
    bn(x)
    after = bn._mean.numpy()
    assert not np.allclose(before, after)
    assert np.all(after > 0)


def test_embedding_grad_is_dense_rowwise():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor(np.array([1, 1, 3], np.int64))
    out = emb(ids)
    paddle.sum(out).backward()
    g = np.asarray(emb.weight.grad)
    assert g.shape == (10, 4)
    np.testing.assert_allclose(g[1], 2.0)  # id 1 used twice
    np.testing.assert_allclose(g[3], 1.0)
    np.testing.assert_allclose(g[0], 0.0)


def test_dygraph_static_parity_linear():
    """Same init -> same forward result in both modes (reference
    dygraph_to_static parity tests)."""
    w = np.random.rand(4, 2).astype(np.float32)
    b = np.random.rand(2).astype(np.float32)
    xv = np.random.rand(3, 4).astype(np.float32)

    lin = nn.Linear(4, 2)
    lin.set_state_dict({"weight": w, "bias": b})
    dy_out = lin(paddle.to_tensor(xv)).numpy()

    paddle.enable_static()
    try:
        import paddle_tpu.fluid as fluid
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        out = fluid.layers.fc(
            x, 2,
            param_attr=paddle.ParamAttr(
                initializer=paddle.initializer.NumpyArrayInitializer(w)),
            bias_attr=paddle.ParamAttr(
                initializer=paddle.initializer.NumpyArrayInitializer(b)))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        st_out, = exe.run(feed={"x": xv}, fetch_list=[out])
    finally:
        paddle.disable_static()
    np.testing.assert_allclose(dy_out, st_out, rtol=1e-5)
