"""Executor + Program basics: feed/fetch, init, persistable state.

Modeled on reference tests: fluid/tests/unittests/test_executor_and_mul.py,
test_fetch_var.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def test_mul_feed_fetch():
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    y = fluid.layers.data(name="y", shape=[3], dtype="float32")
    out = fluid.layers.elementwise_add(x, y)
    exe = fluid.Executor()
    xv = np.random.rand(4, 3).astype(np.float32)
    yv = np.random.rand(4, 3).astype(np.float32)
    res, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[out])
    np.testing.assert_allclose(res, xv + yv, rtol=1e-6)


def test_fc_shapes_and_param_init():
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    out = fluid.layers.fc(x, size=4)
    assert out.shape == (-1, 4)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    params = fluid.default_main_program().all_parameters()
    assert len(params) == 2  # weight + bias
    res, = exe.run(feed={"x": np.ones((2, 8), np.float32)}, fetch_list=[out])
    assert res.shape == (2, 4)


def test_fill_constant_and_scale():
    c = fluid.layers.fill_constant([2, 2], "float32", 3.0)
    s = fluid.layers.scale(c, scale=2.0, bias=1.0)
    exe = fluid.Executor()
    res, = exe.run(fetch_list=[s])
    np.testing.assert_allclose(res, np.full((2, 2), 7.0))


def test_persistable_state_updates():
    # counter += 1 per run, state carried in scope across runs
    counter = fluid.layers.create_global_var([1], 0.0, "float32",
                                             persistable=True, name="ctr")
    fluid.layers.increment(counter, value=1.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for expect in (1.0, 2.0, 3.0):
        res, = exe.run(fetch_list=[counter])
        assert float(res[0]) == expect


def test_uniform_random_seeded_determinism():
    paddle.seed(42)
    u = fluid.layers.uniform_random([16], min=-1, max=1)
    exe = fluid.Executor()
    a, = exe.run(fetch_list=[u])
    paddle.seed(42)
    b, = exe.run(fetch_list=[u])
    np.testing.assert_array_equal(a, b)
    c, = exe.run(fetch_list=[u])  # different key on next run
    assert not np.array_equal(a, c)


def test_program_clone_for_test_strips_dropout_randomness():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    d = fluid.layers.dropout(x, dropout_prob=0.5,
                             dropout_implementation="upscale_in_train")
    test_prog = fluid.default_main_program().clone(for_test=True)
    exe = fluid.Executor()
    xv = np.ones((2, 4), np.float32)
    res, = exe.run(test_prog, feed={"x": xv}, fetch_list=[d])
    np.testing.assert_allclose(res, xv)


def test_save_load_persistables(tmp_path):
    w = fluid.layers.create_global_var([4], 0.0, "float32", persistable=True,
                                       name="w_state")
    fluid.layers.increment(w, value=2.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[w])
    fluid.io.save_persistables(exe, str(tmp_path), fluid.default_main_program())
    paddle.global_scope().set("w_state", np.zeros(4, np.float32))
    fluid.io.load_persistables(exe, str(tmp_path), fluid.default_main_program())
    np.testing.assert_allclose(paddle.global_scope().numpy("w_state"),
                               np.full(4, 2.0))


def test_int64_feed_overflow_guard():
    """int64 ids live as int32 on device (framework/dtype.py policy): in-range
    int64 feeds cast silently; out-of-range ids raise instead of truncating."""
    import pytest
    ids = fluid.layers.data(name="big_ids", shape=[4], dtype="int64")
    out = fluid.layers.cast(ids, "float32")
    exe = fluid.Executor()
    ok = np.array([[1, 2, 3, 2**31 - 1]], np.int64)
    res, = exe.run(feed={"big_ids": ok}, fetch_list=[out])
    np.testing.assert_allclose(res, ok.astype(np.float32))
    bad = np.array([[1, 2, 3, 2**31 + 7]], np.int64)
    with pytest.raises(ValueError, match="int32 range"):
        exe.run(feed={"big_ids": bad}, fetch_list=[out])
