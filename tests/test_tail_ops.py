"""OpTest coverage for the round-2 tail ops: interp v1/v2, geometry,
sampled softmax, hashing, fused ops, quantize, random, optimizer tail,
metric tail (reference per-op unittests: test_bilinear_interp_v2_op.py,
test_affine_grid_op.py, test_nce.py, test_hash_op.py,
test_fused_multihead_matmul_op.py, test_fake_quantize_op.py,
test_mean_iou.py, test_chunk_eval_op.py, ...)."""
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from op_test import check_output, check_grad, run_op

R = np.random.RandomState(0)


# --- interpolation ---------------------------------------------------------

def test_interp_v2_family_shapes():
    x = R.randn(2, 3, 8, 8).astype(np.float32)
    for op in ("nearest_interp_v2", "bilinear_interp_v2",
               "bicubic_interp_v2"):
        out = run_op(op, {"X": [x]}, {"out_h": 16, "out_w": 12})
        assert out["Out"][0].shape == (2, 3, 16, 12)
    x1 = R.randn(2, 3, 8).astype(np.float32)
    out = run_op("linear_interp_v2", {"X": [x1]}, {"out_w": 16})
    assert out["Out"][0].shape == (2, 3, 16)
    x3 = R.randn(1, 2, 4, 4, 4).astype(np.float32)
    out = run_op("trilinear_interp_v2", {"X": [x3]},
                 {"out_d": 8, "out_h": 6, "out_w": 2})
    assert out["Out"][0].shape == (1, 2, 8, 6, 2)


def test_bilinear_interp_v2_values_and_grad():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = run_op("bilinear_interp_v2", {"X": [x]}, {"out_h": 2, "out_w": 2})
    np.testing.assert_allclose(
        np.asarray(out["Out"][0]).reshape(2, 2),
        [[2.5, 4.5], [10.5, 12.5]], atol=1e-5)
    check_grad("bilinear_interp_v2", {"X": [x]},
               {"out_h": 2, "out_w": 2}, wrt=["X"])


def test_trilinear_align_corners():
    x = np.arange(8, dtype=np.float32).reshape(1, 1, 2, 2, 2)
    out = run_op("trilinear_interp_v2", {"X": [x]},
                 {"out_d": 3, "out_h": 3, "out_w": 3,
                  "align_corners": True})
    got = np.asarray(out["Out"][0]).reshape(3, 3, 3)
    assert got[0, 0, 0] == 0.0 and got[2, 2, 2] == 7.0
    assert abs(got[1, 1, 1] - 3.5) < 1e-5


# --- geometry --------------------------------------------------------------

def test_affine_grid_identity():
    theta = np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1))
    out = run_op("affine_grid", {"Theta": [theta]},
                 {"output_shape": [2, 1, 3, 3], "align_corners": True})
    grid = np.asarray(out["Output"][0])
    assert grid.shape == (2, 3, 3, 2)
    np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-6)
    np.testing.assert_allclose(grid[0, 2, 2], [1, 1], atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, 1], [0, 0], atol=1e-6)
    check_grad("affine_grid", {"Theta": [theta]},
               {"output_shape": [2, 1, 3, 3]}, wrt=["Theta"],
               out_slots=("Output",))


def test_psroi_pool():
    oc, ph, pw = 2, 2, 2
    x = R.randn(1, oc * ph * pw, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 7, 7]], np.float32)
    out = run_op("psroi_pool", {"X": [x], "ROIs": [rois]},
                 {"pooled_height": ph, "pooled_width": pw,
                  "output_channels": oc, "spatial_scale": 1.0})
    assert out["Out"][0].shape == (1, oc, ph, pw)


def test_psroi_pool_batched_rois_num():
    """With batch N>1, each ROI must pool from ITS image (RoisNum routing),
    not image 0."""
    oc, ph, pw = 2, 2, 2
    x0 = np.full((oc * ph * pw, 8, 8), 1.0, np.float32)
    x1 = np.full((oc * ph * pw, 8, 8), 3.0, np.float32)
    x = np.stack([x0, x1])
    rois = np.array([[0, 0, 7, 7], [0, 0, 7, 7]], np.float32)
    nums = np.array([1, 1], np.int32)
    out = run_op("psroi_pool",
                 {"X": [x], "ROIs": [rois], "RoisNum": [nums]},
                 {"pooled_height": ph, "pooled_width": pw,
                  "output_channels": oc, "spatial_scale": 1.0})
    got = np.asarray(out["Out"][0])
    np.testing.assert_allclose(got[0], 1.0, atol=1e-5)
    np.testing.assert_allclose(got[1], 3.0, atol=1e-5)
    with pytest.raises(ValueError, match="RoisNum"):
        run_op("psroi_pool", {"X": [x], "ROIs": [rois]},
               {"pooled_height": ph, "pooled_width": pw,
                "output_channels": oc, "spatial_scale": 1.0})


def test_prroi_pool_batched_rois():
    x = np.stack([np.full((3, 8, 8), 5.0, np.float32),
                  np.full((3, 8, 8), 9.0, np.float32)])
    rois = np.array([[1, 1, 6, 6], [1, 1, 6, 6]], np.float32)
    nums = np.array([1, 1], np.int32)
    out = run_op("prroi_pool",
                 {"X": [x], "ROIs": [rois], "BatchRoINums": [nums]},
                 {"pooled_height": 2, "pooled_width": 2,
                  "spatial_scale": 1.0})
    got = np.asarray(out["Out"][0])
    np.testing.assert_allclose(got[0], 5.0, atol=1e-5)
    np.testing.assert_allclose(got[1], 9.0, atol=1e-5)


def test_prroi_pool_constant_region():
    x = np.full((1, 3, 8, 8), 5.0, np.float32)
    rois = np.array([[1, 1, 6, 6]], np.float32)
    out = run_op("prroi_pool", {"X": [x], "ROIs": [rois]},
                 {"pooled_height": 2, "pooled_width": 2,
                  "spatial_scale": 1.0})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), 5.0, atol=1e-5)


def test_deformable_conv_zero_offset_matches_conv():
    x = R.randn(2, 3, 6, 6).astype(np.float32)
    w = R.randn(4, 3, 3, 3).astype(np.float32)
    oh = ow = 4
    off = np.zeros((2, 2 * 9, oh, ow), np.float32)
    mask = np.ones((2, 9, oh, ow), np.float32)
    out = run_op("deformable_conv",
                 {"Input": [x], "Offset": [off], "Mask": [mask],
                  "Filter": [w]},
                 {"strides": [1, 1], "paddings": [0, 0],
                  "dilations": [1, 1], "groups": 1})
    got = np.asarray(out["Output"][0])
    # reference: plain convolution
    ref = run_op("conv2d", {"Input": [x], "Filter": [w]},
                 {"strides": [1, 1], "paddings": [0, 0],
                  "dilations": [1, 1], "groups": 1})
    np.testing.assert_allclose(got, np.asarray(ref["Output"][0]),
                               rtol=1e-4, atol=1e-4)
    v1 = run_op("deformable_conv_v1",
                {"Input": [x], "Offset": [off], "Filter": [w]},
                {"strides": [1, 1], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 1})
    np.testing.assert_allclose(np.asarray(v1["Output"][0]), got,
                               rtol=1e-4, atol=1e-4)


def test_random_crop():
    x = R.randn(4, 10, 10).astype(np.float32)
    out = run_op("random_crop", {"X": [x]}, {"shape": [6, 6]})
    assert out["Out"][0].shape == (4, 6, 6)


# --- sampled softmax / nce -------------------------------------------------

def test_nce_shapes_and_grad():
    b, d, classes = 4, 8, 20
    x = R.randn(b, d).astype(np.float32)
    w = R.randn(classes, d).astype(np.float32)
    bias = R.randn(classes).astype(np.float32)
    lbl = R.randint(0, classes, (b, 1)).astype(np.int64)
    out = run_op("nce", {"Input": [x], "Weight": [w], "Bias": [bias],
                         "Label": [lbl]},
                 {"num_neg_samples": 5, "num_total_classes": classes})
    assert out["Cost"][0].shape == (b, 1)
    assert out["SampleLogits"][0].shape == (b, 6)
    assert np.all(np.asarray(out["Cost"][0]) > 0)


def test_sample_logits():
    b, c = 3, 50
    logits = R.randn(b, c).astype(np.float32)
    lbl = R.randint(0, c, (b, 1)).astype(np.int64)
    out = run_op("sample_logits", {"Logits": [logits], "Labels": [lbl]},
                 {"num_samples": 8})
    assert out["SampledLogits"][0].shape == (b, 9)
    assert np.all(np.asarray(out["SampledLabels"][0]) == 0)


def test_sampling_id():
    probs = np.array([[1.0, 0, 0, 0], [0, 0, 0, 1.0]], np.float32)
    out = run_op("sampling_id", {"X": [probs]}, {})
    ids = np.asarray(out["Out"][0])
    np.testing.assert_array_equal(ids, [0, 3])


# --- hashing / misc features ----------------------------------------------

def test_hash_deterministic_in_range():
    x = R.randint(0, 1000, (5, 3)).astype(np.int64)
    a = np.asarray(run_op("hash", {"X": [x]},
                          {"num_hash": 2, "mod_by": 997})["Out"][0])
    b = np.asarray(run_op("hash", {"X": [x]},
                          {"num_hash": 2, "mod_by": 997})["Out"][0])
    np.testing.assert_array_equal(a, b)
    assert a.shape == (5, 2, 1) and a.min() >= 0 and a.max() < 997


def test_filter_by_instag():
    x = R.randn(4, 3).astype(np.float32)
    tags = np.array([[1], [2], [3], [2]], np.int64)
    filt = np.array([2], np.int64)
    out = run_op("filter_by_instag",
                 {"Ins": [x], "Ins_tag": [tags], "Filter_tag": [filt]}, {})
    got = np.asarray(out["Out"][0])
    np.testing.assert_allclose(got[1], x[1])
    np.testing.assert_allclose(got[0], 0.0)
    np.testing.assert_array_equal(
        np.asarray(out["LossWeight"][0]).reshape(-1), [0, 1, 0, 1])


def test_shuffle_batch():
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    out = run_op("shuffle_batch", {"X": [x]}, {})
    got = np.sort(np.asarray(out["Out"][0]).reshape(-1))
    np.testing.assert_allclose(got, np.arange(8))


def test_match_matrix_tensor():
    x = R.randn(2, 3, 4).astype(np.float32)
    y = R.randn(2, 5, 4).astype(np.float32)
    w = R.randn(4, 2, 4).astype(np.float32)
    out = run_op("match_matrix_tensor", {"X": [x], "Y": [y], "W": [w]}, {})
    got = np.asarray(out["Out"][0])
    assert got.shape == (2, 2, 3, 5)
    ref = np.einsum("bld,dte,bme->btlm", x, w, y)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    check_grad("match_matrix_tensor", {"X": [x], "Y": [y], "W": [w]}, {},
               wrt=["X", "W"])


def test_batch_fc():
    x = R.randn(3, 4, 5).astype(np.float32)
    w = R.randn(3, 5, 2).astype(np.float32)
    b = R.randn(3, 2).astype(np.float32)
    out = run_op("batch_fc", {"Input": [x], "W": [w], "Bias": [b]}, {})
    ref = np.einsum("sbi,sio->sbo", x, w) + b[:, None, :]
    np.testing.assert_allclose(np.asarray(out["Out"][0]), ref,
                               rtol=1e-4, atol=1e-4)


def test_conv_shift():
    x = R.randn(2, 7).astype(np.float32)
    y = R.randn(2, 3).astype(np.float32)
    out = np.asarray(run_op("conv_shift", {"X": [x], "Y": [y]}, {})["Out"][0])
    ref = np.zeros_like(x)
    for b in range(2):
        for i in range(7):
            for j in range(3):
                ref[b, i] += x[b, (i + j - 1) % 7] * y[b, j]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_tree_conv_shape():
    nodes = R.randn(2, 5, 4).astype(np.float32)
    edges = np.array([[[0, 1], [0, 2], [1, 3], [1, 4]]] * 2, np.int64)
    filt = R.randn(4, 3, 6).astype(np.float32)
    out = run_op("tree_conv", {"NodesVector": [nodes], "EdgeSet": [edges],
                               "Filter": [filt]}, {})
    assert out["Out"][0].shape == (2, 5, 6)


# --- fused -----------------------------------------------------------------

def test_multihead_matmul_matches_manual():
    b, s, h, heads = 2, 4, 8, 2
    qkv = R.randn(b, s, 3 * h).astype(np.float32)
    out = run_op("multihead_matmul", {"Input": [qkv]},
                 {"head_number": heads, "alpha": 1.0 / np.sqrt(h // heads)})
    got = np.asarray(out["Out"][0])
    assert got.shape == (b, s, h)
    # manual attention
    q, k, v = np.split(qkv, 3, axis=-1)
    hd = h // heads
    def sp(t):
        return t.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
    qh, kh, vh = map(sp, (q, k, v))
    sc = np.einsum("bnsd,bntd->bnst", qh, kh) / np.sqrt(hd)
    e = np.exp(sc - sc.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bnst,bntd->bnsd", p, vh).transpose(0, 2, 1, 3) \
        .reshape(b, s, h)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_fused_embedding_eltwise_layernorm():
    vocab, d = 20, 8
    w1 = R.randn(vocab, d).astype(np.float32)
    w2 = R.randn(vocab, d).astype(np.float32)
    ids1 = R.randint(0, vocab, (2, 5, 1)).astype(np.int64)
    ids2 = R.randint(0, vocab, (2, 5, 1)).astype(np.int64)
    scale = np.ones(d, np.float32)
    bias = np.zeros(d, np.float32)
    out = run_op("fused_embedding_eltwise_layernorm",
                 {"Ids": [ids1, ids2], "Embs": [w1, w2],
                  "Scale": [scale], "Bias": [bias]}, {"epsilon": 1e-5})
    got = np.asarray(out["Out"][0])
    assert got.shape == (2, 5, d)
    np.testing.assert_allclose(got.mean(-1), 0.0, atol=1e-4)


def test_fused_embedding_seq_pool():
    w = R.randn(10, 4).astype(np.float32)
    ids = R.randint(0, 10, (3, 5, 1)).astype(np.int64)
    sl = np.array([5, 3, 0], np.int64)
    out = run_op("fused_embedding_seq_pool",
                 {"W": [w], "Ids": [ids], "SeqLen": [sl]}, {})
    got = np.asarray(out["Out"][0])
    ref0 = w[ids[0, :, 0]].sum(0)
    ref1 = w[ids[1, :3, 0]].sum(0)
    np.testing.assert_allclose(got[0], ref0, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got[1], ref1, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got[2], 0.0, atol=1e-6)


def test_fusion_repeated_fc_relu():
    x = R.randn(3, 4).astype(np.float32)
    w1 = R.randn(4, 5).astype(np.float32)
    b1 = R.randn(5).astype(np.float32)
    w2 = R.randn(5, 2).astype(np.float32)
    b2 = R.randn(2).astype(np.float32)
    out = run_op("fusion_repeated_fc_relu",
                 {"X": [x], "W": [w1, w2], "Bias": [b1, b2]}, {})
    ref = np.maximum(np.maximum(x @ w1 + b1, 0) @ w2 + b2, 0)
    np.testing.assert_allclose(np.asarray(out["Out"][0]), ref,
                               rtol=1e-4, atol=1e-4)


def test_fusion_squared_mat_sub():
    x = R.randn(3, 4).astype(np.float32)
    y = R.randn(4, 5).astype(np.float32)
    out = run_op("fusion_squared_mat_sub", {"X": [x], "Y": [y]},
                 {"scalar": 0.5})
    ref = 0.5 * ((x @ y) ** 2 - (x * x) @ (y * y))
    np.testing.assert_allclose(np.asarray(out["Out"][0]), ref,
                               rtol=1e-4, atol=1e-4)


def test_fusion_gru_lstm_shapes():
    b, t, f, h = 2, 5, 3, 4
    x = R.randn(b, t, f).astype(np.float32)
    wx_g = R.randn(f, 3 * h).astype(np.float32)
    wh_g = R.randn(h, 3 * h).astype(np.float32)
    out = run_op("fusion_gru", {"X": [x], "WeightX": [wx_g],
                                "WeightH": [wh_g]}, {})
    assert out["Hidden"][0].shape == (b, t, h)
    wx_l = R.randn(f, 4 * h).astype(np.float32)
    wh_l = R.randn(h, 4 * h).astype(np.float32)
    out = run_op("fusion_lstm", {"X": [x], "WeightX": [wx_l],
                                 "WeightH": [wh_l]}, {})
    assert out["Hidden"][0].shape == (b, t, h)
    assert out["Cell"][0].shape == (b, t, h)


def test_fusion_seqpool_concat():
    x1 = R.randn(2, 4, 3).astype(np.float32)
    x2 = R.randn(2, 4, 5).astype(np.float32)
    out = run_op("fusion_seqpool_concat", {"X": [x1, x2]},
                 {"pooltype": "SUM"})
    got = np.asarray(out["Out"][0])
    assert got.shape == (2, 8)
    np.testing.assert_allclose(got[:, :3], x1.sum(1), rtol=1e-5, atol=1e-5)


def test_lstmp():
    b, t, d, p = 2, 4, 6, 3
    x = R.randn(b, t, 4 * d).astype(np.float32)
    w = R.randn(p, 4 * d).astype(np.float32)
    pw = R.randn(d, p).astype(np.float32)
    out = run_op("lstmp", {"Input": [x], "Weight": [w], "ProjWeight": [pw]},
                 {})
    assert out["Projection"][0].shape == (b, t, p)
    assert out["Cell"][0].shape == (b, t, d)


# --- quantize --------------------------------------------------------------

def test_fake_quantize_abs_max():
    x = R.randn(4, 5).astype(np.float32)
    out = run_op("fake_quantize_abs_max", {"X": [x]}, {"bit_length": 8})
    scale = float(np.abs(x).max())
    ref = np.round(x / scale * 127)
    np.testing.assert_allclose(np.asarray(out["Out"][0]), ref, atol=0.5)
    np.testing.assert_allclose(np.asarray(out["OutScale"][0]), [scale],
                               rtol=1e-6)
    deq = run_op("fake_dequantize_max_abs",
                 {"X": [ref.astype(np.float32)],
                  "Scale": [np.array([scale], np.float32)]},
                 {"max_range": 127.0})
    np.testing.assert_allclose(np.asarray(deq["Out"][0]), x, atol=scale/100)


def test_fake_quantize_range_abs_max_window():
    """FindRangeAbsMaxFunctor semantics (fake_quantize_op.cc:236): the scale
    is the running max over a window_size ring of per-batch abs-maxes, and
    the ring persists across steps via InScales/OutScales."""
    window = 4
    scales = np.zeros(window, np.float32)
    seen = []
    for step, amp in enumerate([2.0, 8.0, 1.0, 0.5, 0.25, 0.125]):
        x = np.array([[amp, -amp / 2]], np.float32)
        out = run_op("fake_quantize_range_abs_max",
                     {"X": [x], "Iter": [np.array([step], np.int64)],
                      "InScales": [scales]},
                     {"bit_length": 8, "window_size": window})
        scales = np.asarray(out["OutScales"][0])
        seen.append(amp)
        live = seen[-window:] + [0.0] * (window - len(seen))
        np.testing.assert_allclose(np.asarray(out["OutScale"][0]),
                                   [max(live)], rtol=1e-6)
    # after 6 steps the window holds steps 2..5: the early 8.0 max evicted
    assert abs(float(scales.max()) - 1.0) < 1e-6
    # eval (is_test) reads the window max but must NOT clobber the ring
    ev = run_op("fake_quantize_range_abs_max",
                {"X": [np.array([[99.0]], np.float32)],
                 "Iter": [np.array([6], np.int64)], "InScales": [scales]},
                {"bit_length": 8, "window_size": window, "is_test": True})
    np.testing.assert_allclose(np.asarray(ev["OutScales"][0]), scales)
    np.testing.assert_allclose(np.asarray(ev["OutScale"][0]),
                               [scales.max()], rtol=1e-6)


def test_interp_scalar_scale_list_broadcasts():
    x = R.randn(1, 2, 4, 6).astype(np.float32)
    out = run_op("bilinear_interp_v2", {"X": [x]}, {"scale": [2.0]})
    assert out["Out"][0].shape == (1, 2, 8, 12)


def test_expand_as_v1_target_tensor_slot():
    x = np.array([[1.0], [2.0]], np.float32)
    tgt = np.zeros((2, 3), np.float32)
    out = run_op("expand_as", {"X": [x], "target_tensor": [tgt]}, {})
    np.testing.assert_allclose(np.asarray(out["Out"][0]),
                               [[1, 1, 1], [2, 2, 2]])


def test_fake_channel_wise_quantize():
    x = R.randn(3, 4).astype(np.float32)
    out = run_op("fake_channel_wise_quantize_abs_max", {"X": [x]},
                 {"bit_length": 8, "quant_axis": 0})
    scale = np.abs(x).max(axis=1)
    np.testing.assert_allclose(np.asarray(out["OutScale"][0]), scale,
                               rtol=1e-6)
    deq = run_op("fake_channel_wise_dequantize_max_abs",
                 {"X": [np.asarray(out["Out"][0])], "Scales": [scale]},
                 {"quant_bits": [8], "quant_axis": 0})
    np.testing.assert_allclose(np.asarray(deq["Out"][0]), x,
                               atol=float(scale.max()) / 100)


def test_moving_average_abs_max_scale():
    x = np.array([[1.0, -3.0]], np.float32)
    out = run_op("moving_average_abs_max_scale",
                 {"X": [x], "InState": [np.array(1.0, np.float32)],
                  "InAccum": [np.array(2.0, np.float32)]},
                 {"moving_rate": 0.9})
    np.testing.assert_allclose(np.asarray(out["OutState"][0]), 1.9)
    np.testing.assert_allclose(np.asarray(out["OutAccum"][0]), 4.8,
                               rtol=1e-6)


# --- random / creation -----------------------------------------------------

def test_bernoulli_randperm_empty_fill_allclose():
    p = np.full((1000,), 0.3, np.float32)
    out = np.asarray(run_op("bernoulli", {"X": [p]}, {})["Out"][0])
    assert set(np.unique(out)) <= {0.0, 1.0}
    assert 0.2 < out.mean() < 0.4
    perm = np.asarray(run_op("randperm", {}, {"n": 10})["Out"][0])
    np.testing.assert_array_equal(np.sort(perm), np.arange(10))
    e = run_op("empty", {}, {"shape": [2, 3], "dtype": "float32"})
    assert e["Out"][0].shape == (2, 3)
    f = run_op("fill", {}, {"shape": [2, 2],
                            "value": [1.0, 2.0, 3.0, 4.0],
                            "dtype": "float32"})
    np.testing.assert_allclose(np.asarray(f["Out"][0]),
                               [[1, 2], [3, 4]])
    a = run_op("allclose", {"Input": [np.ones(3, np.float32)],
                            "Other": [np.ones(3, np.float32) + 1e-9]}, {})
    assert bool(np.asarray(a["Out"][0]))


def test_batch_size_like_random():
    ref = np.zeros((7, 2), np.float32)
    u = run_op("uniform_random_batch_size_like", {"Input": [ref]},
               {"shape": [1, 5], "min": 0.0, "max": 1.0})
    assert u["Out"][0].shape == (7, 5)
    g = run_op("gaussian_random_batch_size_like", {"Input": [ref]},
               {"shape": [1, 4], "mean": 10.0, "std": 0.1})
    arr = np.asarray(g["Out"][0])
    assert arr.shape == (7, 4) and 9 < arr.mean() < 11


# --- control flow helpers --------------------------------------------------

def test_coalesce_tensor_roundtrip():
    xs = [R.randn(2, 3).astype(np.float32),
          R.randn(4).astype(np.float32)]
    out = run_op("coalesce_tensor", {"Input": xs}, {})
    assert out["FusedOutput"][0].shape == (10,)
    for got, x in zip(out["Output"], xs):
        np.testing.assert_allclose(np.asarray(got), x)


def test_select_input_output():
    xs = [np.zeros((2, 2), np.float32), np.ones((2, 2), np.float32)]
    m = np.array([1], np.int32)
    out = run_op("select_input", {"X": xs, "Mask": [m]}, {})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), 1.0)
    outs = run_op("select_output", {"X": [xs[1]], "Mask": [m]},
                  {"num_outputs": 2})
    np.testing.assert_allclose(np.asarray(outs["Out"][0]), 0.0)
    np.testing.assert_allclose(np.asarray(outs["Out"][1]), 1.0)


def test_py_func():
    from paddle_tpu.ops.tail_ops import register_py_func
    register_py_func(7, lambda a: a * 2 + 1)
    x = R.randn(3, 2).astype(np.float32)
    out = run_op("py_func", {"X": [x]},
                 {"forward_callable_id": 7,
                  "out_shapes": [[3, 2]], "out_dtypes": ["float32"]})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), x * 2 + 1,
                               rtol=1e-6)


def test_print_identity():
    x = R.randn(2, 2).astype(np.float32)
    out = run_op("print", {"In": [x]}, {"message": "dbg: "})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), x)


def test_write_read_array_aliases():
    from paddle_tpu.ops import registry
    assert registry.has("write_to_array")
    assert registry.has("read_from_array")
    assert registry.has("expand_as")
    assert registry.has("multiclass_nms2")


# --- optimizer tail --------------------------------------------------------

def test_proximal_gd_adagrad():
    p = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, 0.5], np.float32)
    lr = np.array([0.1], np.float32)
    out = run_op("proximal_gd", {"Param": [p], "Grad": [g],
                                 "LearningRate": [lr]},
                 {"l1": 0.0, "l2": 0.0})
    np.testing.assert_allclose(np.asarray(out["ParamOut"][0]),
                               p - 0.1 * g, rtol=1e-6)
    m = np.array([0.1, 0.1], np.float32)
    out = run_op("proximal_adagrad",
                 {"Param": [p], "Grad": [g], "Moment": [m],
                  "LearningRate": [lr]}, {"l1": 0.01, "l2": 0.01})
    assert out["ParamOut"][0].shape == (2,)
    np.testing.assert_allclose(np.asarray(out["MomentOut"][0]),
                               m + g * g, rtol=1e-6)


def test_dgc_ops():
    x = np.array([3.0, 4.0], np.float32)   # norm 5
    out = run_op("dgc_clip_by_norm",
                 {"X": [x], "current_step": [np.array(10.0, np.float32)]},
                 {"rampup_begin_step": 0.0, "max_norm": 1.0})
    np.testing.assert_allclose(np.asarray(out["Out"][0]), x / 5.0,
                               rtol=1e-5)
    p = np.array([1.0], np.float32)
    g = np.array([0.1], np.float32)
    v = np.array([0.0], np.float32)
    out = run_op("dgc_momentum",
                 {"Param": [p], "Grad": [g], "Velocity": [v],
                  "LearningRate": [np.array([0.1], np.float32)],
                  "current_step": [np.array(0.0, np.float32)]},
                 {"mu": 0.9})
    np.testing.assert_allclose(np.asarray(out["ParamOut"][0]),
                               [1.0 - 0.01], rtol=1e-5)


def test_dgc_op_sparsifies_with_residual():
    """dgc_op.h semantics: u=m*u+g, v+=u; top-(1-s) of |v| leaves as
    EncodeGrad, the rest stays in v (residual feedback); selected slots
    reset in u too (momentum factor masking)."""
    rng = np.random.RandomState(0)
    g = rng.randn(64).astype(np.float32)
    u = rng.randn(64).astype(np.float32) * 0.1
    v = rng.randn(64).astype(np.float32) * 0.1
    out = run_op("dgc",
                 {"U": [u], "V": [v], "Grad": [g],
                  "current_step": [np.array(5.0, np.float32)]},
                 {"m": 0.9, "rampup_begin_step": 0.0, "rampup_step": 1.0,
                  "sparsity": [0.75]})
    enc = np.asarray(out["EncodeGrad"][0])
    u_out = np.asarray(out["UOut"][0])
    v_out = np.asarray(out["VOut"][0])
    u2 = 0.9 * u + g
    v2 = v + u2
    # conservation: encoded + residual == full accumulated gradient
    np.testing.assert_allclose(enc + v_out, v2, rtol=1e-5, atol=1e-6)
    kept = enc != 0
    assert 0 < kept.sum() <= 0.5 * 64  # ~25% kept (sampled threshold)
    assert np.all(v_out[kept] == 0) and np.all(u_out[kept] == 0)
    np.testing.assert_allclose(u_out[~kept], u2[~kept], rtol=1e-5)
    # every surviving |entry| >= every dropped |entry| region boundary
    assert np.abs(v2[kept]).min() >= np.abs(v2[~kept]).max() - 1e-6


def test_dgc_op_passthrough_before_rampup():
    g = np.array([1.0, -2.0, 3.0], np.float32)
    u = np.zeros(3, np.float32)
    v = np.zeros(3, np.float32)
    out = run_op("dgc",
                 {"U": [u], "V": [v], "Grad": [g],
                  "current_step": [np.array(3.0, np.float32)]},
                 {"m": 0.9, "rampup_begin_step": 10.0, "rampup_step": 4.0,
                  "sparsity": [0.75, 0.9375]})
    np.testing.assert_allclose(np.asarray(out["EncodeGrad"][0]), g)
    np.testing.assert_allclose(np.asarray(out["VOut"][0]), np.zeros(3))


def test_dgc_momentum_switches_to_sgd_after_rampup():
    p = np.array([1.0], np.float32)
    g = np.array([0.1], np.float32)
    v = np.array([0.5], np.float32)   # pre-existing velocity
    common = {"Param": [p], "Grad": [g], "Velocity": [v],
              "LearningRate": [np.array([0.1], np.float32)]}
    before = run_op("dgc_momentum",
                    {**common,
                     "current_step": [np.array(2.0, np.float32)]},
                    {"mu": 0.9, "rampup_begin_step": 5.0})
    after = run_op("dgc_momentum",
                   {**common,
                    "current_step": [np.array(7.0, np.float32)]},
                   {"mu": 0.9, "rampup_begin_step": 5.0})
    # before: momentum (v2 = .45+.1 = .55, p -= .055)
    np.testing.assert_allclose(np.asarray(before["ParamOut"][0]), [0.945],
                               rtol=1e-5)
    # after: plain sgd (p -= lr*g), velocity untouched
    np.testing.assert_allclose(np.asarray(after["ParamOut"][0]), [0.99],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(after["VelocityOut"][0]), [0.5],
                               rtol=1e-5)


# --- metric tail -----------------------------------------------------------

def test_mean_iou():
    pred = np.array([0, 1, 1, 2], np.int32)
    lab = np.array([0, 1, 2, 2], np.int32)
    out = run_op("mean_iou", {"Predictions": [pred], "Labels": [lab]},
                 {"num_classes": 3})
    # class0: 1/1, class1: 1/2, class2: 1/2 → mean = 2/3
    np.testing.assert_allclose(float(np.asarray(out["OutMeanIou"][0]).reshape(-1)[0]),
                               2 / 3, rtol=1e-5)


def test_positive_negative_pair():
    s = np.array([0.9, 0.1, 0.8, 0.6], np.float32)
    l = np.array([1.0, 0.0, 1.0, 0.0], np.float32)
    q = np.array([7, 7, 7, 7], np.int64)
    out = run_op("positive_negative_pair",
                 {"Score": [s], "Label": [l], "QueryID": [q]}, {})
    assert float(np.asarray(out["PositivePair"][0]).reshape(-1)[0]) == 4.0
    assert float(np.asarray(out["NegativePair"][0]).reshape(-1)[0]) == 0.0


def test_chunk_eval_iob():
    # tags: B-0=0 I-0=1 B-1=2 I-1=3 O=4 ; one seq
    inf = np.array([[0, 1, 4, 2, 3]], np.int64)
    lab = np.array([[0, 1, 4, 2, 4]], np.int64)
    out = run_op("chunk_eval", {"Inference": [inf], "Label": [lab]},
                 {"num_chunk_types": 2, "chunk_scheme": "IOB"})
    # inferred chunks: (0,2,0),(3,5,1); label chunks: (0,2,0),(3,4,1)
    assert int(np.asarray(out["NumInferChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumLabelChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumCorrectChunks"][0]).reshape(-1)[0]) == 1
    np.testing.assert_allclose(float(np.asarray(out["Precision"][0]).reshape(-1)[0]), 0.5)


def test_chunk_eval_ioe():
    # IOE: I-0=0 E-0=1 I-1=2 E-1=3 O=4
    inf = np.array([[0, 1, 2, 3]], np.int64)   # chunks (0,1,0),(2,3,1)
    lab = np.array([[0, 1, 4, 3]], np.int64)   # chunks (0,1,0),(3,3,1)
    out = run_op("chunk_eval", {"Inference": [inf], "Label": [lab]},
                 {"num_chunk_types": 2, "chunk_scheme": "IOE"})
    assert int(np.asarray(out["NumInferChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumLabelChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumCorrectChunks"][0]).reshape(-1)[0]) == 1
    np.testing.assert_allclose(float(np.asarray(out["Precision"][0]).reshape(-1)[0]), 0.5)


def test_chunk_eval_iobes():
    # IOBES: B-t=4t I-t=4t+1 E-t=4t+2 S-t=4t+3, O=8
    inf = np.array([[3, 8, 4, 5, 6]], np.int64)  # (0,0,0),(2,4,1)
    lab = np.array([[3, 8, 4, 5, 8]], np.int64)  # (0,0,0),(2,3,1)
    out = run_op("chunk_eval", {"Inference": [inf], "Label": [lab]},
                 {"num_chunk_types": 2, "chunk_scheme": "IOBES"})
    assert int(np.asarray(out["NumInferChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumCorrectChunks"][0]).reshape(-1)[0]) == 1
    np.testing.assert_allclose(float(np.asarray(out["Recall"][0]).reshape(-1)[0]), 0.5)


def test_chunk_eval_plain_groups_runs():
    # plain: consecutive same-type tokens are ONE chunk (chunk_eval_op.h
    # state machine with num_tag_types=1), not per-token chunks
    inf = np.array([[0, 0, 1, 2]], np.int64)   # runs (0,1,0),(2,2,1); 2=O
    lab = np.array([[0, 0, 1, 2]], np.int64)
    out = run_op("chunk_eval", {"Inference": [inf], "Label": [lab]},
                 {"num_chunk_types": 2, "chunk_scheme": "plain"})
    assert int(np.asarray(out["NumInferChunks"][0]).reshape(-1)[0]) == 2
    assert int(np.asarray(out["NumCorrectChunks"][0]).reshape(-1)[0]) == 2
    np.testing.assert_allclose(float(np.asarray(out["F1-Score"][0]).reshape(-1)[0]), 1.0)


def test_chunk_eval_excluded_types():
    # same data as the IOB test; excluding type 0 removes the only match
    inf = np.array([[0, 1, 4, 2, 3]], np.int64)
    lab = np.array([[0, 1, 4, 2, 4]], np.int64)
    out = run_op("chunk_eval", {"Inference": [inf], "Label": [lab]},
                 {"num_chunk_types": 2, "chunk_scheme": "IOB",
                  "excluded_chunk_types": [0]})
    assert int(np.asarray(out["NumInferChunks"][0]).reshape(-1)[0]) == 1
    assert int(np.asarray(out["NumLabelChunks"][0]).reshape(-1)[0]) == 1
    assert int(np.asarray(out["NumCorrectChunks"][0]).reshape(-1)[0]) == 0
    np.testing.assert_allclose(float(np.asarray(out["Precision"][0]).reshape(-1)[0]), 0.0)


def test_chunk_eval_unknown_scheme_raises():
    inf = np.array([[0]], np.int64)
    with pytest.raises(Exception, match="chunk scheme"):
        run_op("chunk_eval", {"Inference": [inf], "Label": [inf]},
               {"num_chunk_types": 2, "chunk_scheme": "BIO2"})


def test_teacher_student_sigmoid_loss():
    x = np.array([0.0, 2.0], np.float32)
    lbl = np.array([1.0, 0.0], np.float32)
    out = run_op("teacher_student_sigmoid_loss",
                 {"X": [x], "Label": [lbl]}, {})
    got = np.asarray(out["Y"][0]).reshape(-1)
    sig = 1 / (1 + np.exp(-x))
    ref = -lbl * np.log(sig + 1e-9) - (1 - lbl) * np.log(1 - sig + 1e-9)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_op_count_target():
    from paddle_tpu.ops import registry
    assert len(registry.all_ops()) >= 375
