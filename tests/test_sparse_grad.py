"""embedding(is_sparse=True): SelectedRows-equivalent row-sparse grads.

Mirrors reference tests test_lookup_table_op.py (sparse grad branch) and the
sparse optimizer tests (test_adam_op.py lazy_mode, test_sgd_op.py
SelectedRows): parity between is_sparse=True and dense training for SGD
(exact) and row-touched-only semantics for adam/adagrad/momentum."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.framework.scope import global_scope


def _build_and_train(is_sparse, opt_fn, steps=5, fetch_emb="emb_w"):
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program(); pm._startup_program = pm.Program()
    sm._reset_global_scope(); unique_name.switch()
    paddle.seed(7)
    ids = layers.data(name="ids", shape=[4], dtype="int64")
    y = layers.data(name="y", shape=[1], dtype="float32")
    emb = layers.embedding(ids, size=[100, 8], is_sparse=is_sparse,
                           param_attr=paddle.ParamAttr(name="emb_w"))
    feat = layers.reshape(emb, [-1, 32])
    pred = layers.fc(feat, 1, param_attr=paddle.ParamAttr(name="fc_w"))
    loss = layers.mean(layers.square_error_cost(pred, y))
    opt_fn().minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, 20, (16, 4)).astype(np.int64)
    y_np = rng.randn(16, 1).astype(np.float32)
    losses = []
    for _ in range(steps):
        lv, = exe.run(feed={"ids": ids_np, "y": y_np}, fetch_list=[loss])
        losses.append(float(lv))
    w = np.asarray(global_scope().find(fetch_emb))
    return losses, w, ids_np


@pytest.mark.parametrize("opt", [
    lambda: paddle.optimizer.SGD(learning_rate=0.1),
    lambda: paddle.optimizer.Adam(learning_rate=0.05),
    lambda: paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9),
])
def test_sparse_matches_dense_training(opt):
    l_dense, w_dense, ids = _build_and_train(False, opt)
    l_sparse, w_sparse, _ = _build_and_train(True, opt)
    np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-4, atol=1e-5)
    touched = np.unique(ids)
    np.testing.assert_allclose(w_sparse[touched], w_dense[touched],
                               rtol=1e-4, atol=1e-5)
    # untouched rows must be bit-identical to init in BOTH modes (sgd) —
    # and in sparse mode they are never even read
    untouched = np.setdiff1d(np.arange(100), touched)
    np.testing.assert_allclose(w_sparse[untouched], w_dense[untouched],
                               rtol=1e-5)


def test_selected_rows_value_semantics():
    import jax.numpy as jnp
    from paddle_tpu.ops.sparse_grad import (SelectedRows, merge_rows,
                                            densify)
    sr = SelectedRows(rows=jnp.asarray([[1., 1.], [2., 2.], [3., 3.]]),
                      ids=jnp.asarray([5, 1, 5], jnp.int32))
    m = merge_rows(sr, 10)
    d = densify(sr, 10)
    np.testing.assert_allclose(np.asarray(d[5]), [4., 4.])
    np.testing.assert_allclose(np.asarray(d[1]), [2., 2.])
    # merged rows sum duplicates; padding ids = vocab are dropped by scatter
    md = densify(m, 10)
    np.testing.assert_allclose(np.asarray(md), np.asarray(d))


def test_two_consumer_sparse_accumulation():
    """Two lookups of the same sparse table accumulate via the sum op's
    SelectedRows branch."""
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program(); pm._startup_program = pm.Program()
    sm._reset_global_scope(); unique_name.switch()
    paddle.seed(0)
    a = layers.data(name="a", shape=[2], dtype="int64")
    b = layers.data(name="b", shape=[2], dtype="int64")
    w_attr = paddle.ParamAttr(name="shared_emb")
    e1 = layers.embedding(a, size=[50, 4], is_sparse=True, param_attr=w_attr)
    e2 = layers.embedding(b, size=[50, 4], is_sparse=True, param_attr=w_attr)
    loss = layers.mean(layers.elementwise_add(e1, e2))
    paddle.optimizer.SGD(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w0 = np.asarray(global_scope().find("shared_emb")).copy()
    a_np = np.array([[1, 2]], np.int64)
    b_np = np.array([[2, 3]], np.int64)
    exe.run(feed={"a": a_np, "b": b_np}, fetch_list=[loss])
    w1 = np.asarray(global_scope().find("shared_emb"))
    moved = np.where(np.abs(w1 - w0).max(axis=1) > 1e-9)[0]
    np.testing.assert_array_equal(moved, [1, 2, 3])
    # id 2 appears in both lookups: twice the step of id 1/3
    d1 = (w0 - w1)[1].max()
    d2 = (w0 - w1)[2].max()
    np.testing.assert_allclose(d2, 2 * d1, rtol=1e-4)
