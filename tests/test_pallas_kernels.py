"""Fused Pallas kernels (ops/pallas/): the ISSUE-17 bit-parity pins.

Both kernels run here under interpret=True (the conftest CPU platform
forces it) — the same kernel bodies Mosaic compiles on hardware:

* fused paged-attention decode (paged_attention.py) is BITWISE identical
  to the dense-gather oracle (ops/paged_ops.paged_attend) across block
  sizes, dtypes (f32/bf16), ragged positions, bounded page-table walks,
  shared/frozen-slot tables, and the int8-KV arm;
* the engine's decode window produces identical tokens with the kernel
  on and off, the kernel-on compiled HLO materializes ZERO dense cache
  views, and the fallback program keeps its zero-KV-copy census
  (serving/audit.py);
* the fused flat-bucket optimizer update (zero_update.py) is BITWISE
  identical to the jitted registry rules for sgd/momentum/adam/adamw,
  and end-to-end `__zero_update__` training is bit-for-bit across ZeRO
  stages 1/2/3 (flat and @LAYERS-rolled buckets) with checkpoints
  portable between the fused and unfused arms in both directions.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from conftest import cpu_mesh_env

import paddle_tpu.fluid as fluid


# ---------------------------------------------------------------------------
# fused paged-attention decode vs the dense-gather oracle
# ---------------------------------------------------------------------------

def _decode_case(rng, bs, b=3, nh=2, hd=16, mb=4, dtype=np.float32):
    nb = b * mb + 2
    pt = rng.permutation(nb)[: b * mb].reshape(b, mb).astype(np.int32)
    pos = rng.randint(0, mb * bs, (b,)).astype(np.int32)
    q = rng.randn(b, nh, 1, hd).astype(dtype)
    kp = rng.randn(2, nb, nh, bs, hd).astype(dtype)
    vp = rng.randn(2, nb, nh, bs, hd).astype(dtype)
    return q, kp, vp, pt, pos


def _assert_bitwise(got, want, tag=""):
    g, w = np.asarray(got), np.asarray(want)
    assert g.dtype == w.dtype and g.shape == w.shape, (tag, g.dtype, w.dtype)
    if g.tobytes() != w.tobytes():
        d = np.max(np.abs(g.astype(np.float64) - w.astype(np.float64)))
        raise AssertionError(f"bitwise mismatch {tag}: maxdiff {d}")


@pytest.mark.parametrize("bs", [8, 16, 32])
def test_fused_decode_bitwise_f32(bs):
    from paddle_tpu.ops.paged_ops import paged_attend
    from paddle_tpu.ops.pallas.paged_attention import fused_paged_attention
    rng = np.random.RandomState(bs)
    q, kp, vp, pt, pos = _decode_case(rng, bs)
    for layer in (0, 1):
        _assert_bitwise(
            fused_paged_attention(q, kp, vp, pt, pos, block_size=bs,
                                  layer=layer),
            paged_attend(q, kp, vp, pt, pos, bs, layer=layer),
            f"bs={bs} layer={layer}")


def test_fused_decode_bitwise_bf16():
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_ops import paged_attend
    from paddle_tpu.ops.pallas.paged_attention import fused_paged_attention
    rng = np.random.RandomState(2)
    q, kp, vp, pt, pos = _decode_case(rng, 16)
    q, kp, vp = (jnp.asarray(a, jnp.bfloat16) for a in (q, kp, vp))
    _assert_bitwise(fused_paged_attention(q, kp, vp, pt, pos, block_size=16),
                    paged_attend(q, kp, vp, pt, pos, 16), "bf16")


def test_fused_decode_ragged_pos_and_bounded_walk():
    """Ragged positions (incl. a slot at pos 0 and one at the last row of
    its last block) and the static max_blocks hint ladder: any hint that
    covers max(pos) is bit-neutral on BOTH read paths (satellite: the
    fallback's gather is bounded by the same hint)."""
    from paddle_tpu.ops.paged_ops import paged_attend
    from paddle_tpu.ops.pallas.paged_attention import fused_paged_attention
    rng = np.random.RandomState(3)
    bs, mb = 8, 4
    q, kp, vp, pt, pos = _decode_case(rng, bs, mb=mb)
    pos = np.array([0, bs * 2 - 1, mb * bs - 1], np.int32)
    full = paged_attend(q, kp, vp, pt, pos, bs)
    need = int(pos.max()) // bs + 1
    for hint in range(need, mb + 1):
        _assert_bitwise(
            paged_attend(q, kp, vp, pt, pos, bs, max_blocks=hint),
            full, f"fallback hint={hint}")
        _assert_bitwise(
            fused_paged_attention(q, kp, vp, pt, pos, block_size=bs,
                                  max_blocks=hint),
            full, f"kernel hint={hint}")


def test_fused_decode_shared_scratch_blocks():
    """Frozen-slot redirect shape: several slots' page tables aliasing
    the SAME physical block (the engine parks retired slots on a shared
    scratch block) must read identically on both paths — the kernel's
    walk is per-slot, so aliased tables are just repeated block ids."""
    from paddle_tpu.ops.paged_ops import paged_attend
    from paddle_tpu.ops.pallas.paged_attention import fused_paged_attention
    rng = np.random.RandomState(4)
    bs, mb = 8, 4
    q, kp, vp, pt, pos = _decode_case(rng, bs, mb=mb)
    pt[1, :] = pt[0, 0]          # slot 1 parked entirely on one block
    pt[2, :] = pt[0, :]          # slot 2 aliases slot 0's table
    _assert_bitwise(
        fused_paged_attention(q, kp, vp, pt, pos, block_size=bs),
        paged_attend(q, kp, vp, pt, pos, bs), "aliased tables")


def test_fused_decode_int8_kv():
    """int8-KV arm: bitwise vs the fallback's folded-dequant contract,
    and numerically equivalent (not bitwise — different reduction
    grouping) to dequantize-then-dense-attend."""
    import jax.numpy as jnp
    from paddle_tpu.models.gpt_decode import _attend
    from paddle_tpu.ops.paged_ops import (dequant_kv, paged_attend,
                                          paged_gather, quantize_kv)
    from paddle_tpu.ops.pallas.paged_attention import fused_paged_attention
    rng = np.random.RandomState(5)
    bs, scale = 16, 8.0
    q, kp, vp, pt, pos = _decode_case(rng, bs)
    ki = np.asarray(quantize_kv(kp, scale))
    vi = np.asarray(quantize_kv(vp, scale))
    assert ki.dtype == np.int8
    want = paged_attend(q, ki, vi, pt, pos, bs, kv_scale=scale)
    got = fused_paged_attention(q, ki, vi, pt, pos, block_size=bs,
                                kv_scale=scale)
    _assert_bitwise(got, want, "int8")
    # reference semantics: materialized dequant + dense attend
    kd = paged_gather(np.asarray(dequant_kv(ki, scale)), pt, 0)
    vd = paged_gather(np.asarray(dequant_kv(vi, scale)), pt, 0)
    mask = np.where(np.arange(kd.shape[2])[None, :] <= pos[:, None],
                    0.0, -np.inf).astype(np.float32)[:, None, None, :]
    ref = _attend(jnp.asarray(q), jnp.asarray(kd), jnp.asarray(vd),
                  jnp.asarray(mask), 1.0 / np.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_int8_kv_pool_quantized_update():
    """paged_update into int8 pools quantizes writes with the abs-max
    grid (quantize_kv) — the values a later read dequantizes exactly."""
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_ops import paged_update, quantize_kv
    rng = np.random.RandomState(6)
    b, nh, bs, hd, nb = 2, 2, 8, 4, 6
    kp = jnp.zeros((1, nb, nh, bs, hd), jnp.int8)
    vp = jnp.zeros((1, nb, nh, bs, hd), jnp.int8)
    pt = np.arange(b * 2, dtype=np.int32).reshape(b, 2)
    pos = np.array([1, bs + 3], np.int32)
    k1 = rng.randn(b, nh, hd).astype(np.float32)
    v1 = rng.randn(b, nh, hd).astype(np.float32)
    kp2, vp2 = paged_update(kp, vp, k1, v1, pt, pos, bs, 0, kv_scale=8.0)
    for i in range(b):
        blk, off = pt[i, pos[i] // bs], pos[i] % bs
        _assert_bitwise(np.asarray(kp2)[0, blk, :, off],
                        np.asarray(quantize_kv(k1[i], 8.0)), f"k slot {i}")
        _assert_bitwise(np.asarray(vp2)[0, blk, :, off],
                        np.asarray(quantize_kv(v1[i], 8.0)), f"v slot {i}")
    with pytest.raises(ValueError):
        paged_update(kp, vp, k1, v1, pt, pos, bs, 0)   # int8 needs a scale


# ---------------------------------------------------------------------------
# engine-level: tokens + HLO census
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_gpt():
    from paddle_tpu.models.gpt import GPTConfig, build_lm_program
    from paddle_tpu.models import gpt_decode
    from paddle_tpu.testing import reset_programs
    reset_programs(seed=0)
    cfg = GPTConfig.tiny()
    cfg.max_position = 64
    build_lm_program(cfg)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return cfg, gpt_decode.params_from_scope(cfg)


def _engine_tokens(cfg, params, **kw):
    from paddle_tpu.serving import DecodeEngine, Request
    from paddle_tpu.serving import audit
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (5, 9, 3)]
    base = dict(max_slots=3, block_size=8, num_blocks=24, max_len=32,
                window=4)
    base.update(kw)
    eng = DecodeEngine(params, cfg, **base)
    try:
        census = audit.decode_gather_census(eng)
        comps = eng.generate(
            [Request(prompt=p, max_new_tokens=6, seed=i)
             for i, p in enumerate(prompts)], timeout=240)
        assert all(c.ok for c in comps), comps
        return [list(c.tokens) for c in comps], census, eng
    finally:
        eng.stop()


def test_engine_kernel_parity_and_census(tiny_gpt):
    """The tentpole acceptance: engine tokens identical with the fused
    kernel on/off, kernel-on window HLO has ZERO dense cache-view
    materializations, fallback window keeps its gather chain AND its
    zero-KV-copy census."""
    from paddle_tpu.serving import audit
    cfg, params = tiny_gpt
    toks_off, census_off, eng_off = _engine_tokens(
        cfg, params, decode_kernel=False)
    toks_on, census_on, _ = _engine_tokens(
        cfg, params, decode_kernel=True)
    assert toks_on == toks_off
    assert census_on["dense_gathers"] == 0, \
        census_on["dense_gather_findings"][:3]
    assert census_off["dense_gathers"] > 0


@pytest.mark.slow  # ~9 s: two engine builds + window compiles; the
# float arm above keeps the tentpole pin fast, the int8 kernel parity
# itself is pinned in test_fused_decode_int8_kv and kernel_smoke.py
def test_engine_kernel_parity_int8(tiny_gpt):
    """int8-KV engine arm: same tokens with the kernel on and off (both
    sides share the folded-dequant contract), dense views gone with the
    kernel on."""
    cfg, params = tiny_gpt
    kw = dict(kv_dtype="int8", kv_scale=8.0)
    toks_off, _, _ = _engine_tokens(cfg, params, decode_kernel=False, **kw)
    toks_on, census_on, _ = _engine_tokens(cfg, params, decode_kernel=True,
                                           **kw)
    assert toks_on == toks_off
    assert census_on["dense_gathers"] == 0


def test_window_max_blocks_hint(tiny_gpt):
    """The engine's static page-table walk bound: power-of-two bucketed,
    covers every live slot's window reach, capped at the table width —
    and floored to the full width on narrow tables (every distinct hint
    is a window recompile; below _LADDER_MIN_BLOCKS columns the bounded
    walk saves less than one recompile costs)."""
    from paddle_tpu.serving import DecodeEngine

    class _S:
        def __init__(self, pos):
            self.pos = pos

    cfg, params = tiny_gpt
    eng = DecodeEngine(params, cfg, max_slots=3, block_size=8,
                       num_blocks=24, max_len=32, window=4)
    try:
        mb = eng.cache.config.max_blocks_per_slot
        # narrow table (mb=4 <= floor): hint pinned at full width — ONE
        # compiled window regardless of slot positions
        eng._slots = {0: _S(0)}
        assert eng._window_max_blocks() == mb
        # drop the floor on this instance to exercise the ladder (real
        # configs reach mb > _LADDER_MIN_BLOCKS via max_len, e.g.
        # 2048/16 = 128 columns)
        eng._LADDER_MIN_BLOCKS = 2
        eng._slots = {}
        assert eng._window_max_blocks() == mb          # idle: full width
        eng._slots = {0: _S(0)}
        # pos 0 + window 4 -> needs 1 block -> hint 1
        assert eng._window_max_blocks() == 1
        eng._slots = {0: _S(0), 1: _S(9)}
        # pos 9 + window 4 reaches row 12 -> needs 2 blocks -> hint 2
        assert eng._window_max_blocks() == 2
        eng._slots = {0: _S(31)}
        assert eng._window_max_blocks() == mb          # clamped at width
    finally:
        eng._slots = {}
        eng.stop()


# ---------------------------------------------------------------------------
# fused flat-bucket optimizer update
# ---------------------------------------------------------------------------

def _opt_case(rng, op_type, shape, nesterov=False):
    p = rng.randn(*shape).astype(np.float32)
    g = rng.randn(*shape).astype(np.float32)
    ins = {"Param": [p], "Grad": [g],
           "LearningRate": [np.asarray([1e-3], np.float32)]}
    attrs = {}
    if op_type == "momentum":
        ins["Velocity"] = [rng.randn(*shape).astype(np.float32)]
        attrs = {"mu": 0.9, "use_nesterov": nesterov,
                 "regularization_method": "l2_decay",
                 "regularization_coeff": 1e-4}
    elif op_type in ("adam", "adamw"):
        ins["Moment1"] = [rng.randn(*shape).astype(np.float32)]
        ins["Moment2"] = [np.abs(rng.randn(*shape)).astype(np.float32)]
        ins["Beta1Pow"] = [np.asarray([0.9 ** 3], np.float32)]
        ins["Beta2Pow"] = [np.asarray([0.999 ** 3], np.float32)]
    return ins, attrs


@pytest.mark.parametrize("op_type", ["sgd", "momentum", "adam", "adamw"])
@pytest.mark.parametrize("shape", [(256,), (3, 128)], ids=["flat", "rolled"])
def test_fused_update_bitwise_vs_jitted_rule(op_type, shape):
    """Kernel outputs == the JITTED registry rule, bit for bit, on flat
    [S] and stacked [L, S] buckets. The jitted rule is the oracle because
    __zero_update__ always runs inside the compiled train step — XLA's
    fusion rounding (FMA formation) is part of the contract."""
    import jax
    from paddle_tpu.ops import optimizer_ops  # noqa: F401 (registers)
    from paddle_tpu.ops import registry
    from paddle_tpu.ops.pallas.zero_update import fused_flat_update
    rng = np.random.RandomState(hash(op_type) % 1000)
    ins, attrs = _opt_case(rng, op_type, shape, nesterov=True)
    want = jax.jit(
        lambda: registry.get(op_type).lower(None, ins, attrs))()
    got = jax.jit(lambda: fused_flat_update(op_type, ins, attrs))()
    assert sorted(got) == sorted(want)
    for k in sorted(want):
        _assert_bitwise(got[k][0], want[k][0], f"{op_type} {shape} {k}")


def test_fused_update_supports_gating():
    """SelectedRows grads and unknown op types stay on the registry
    rule; the enable switch honors both the env and the flag."""
    from paddle_tpu.ops.pallas import zero_update as zk
    from paddle_tpu.ops.sparse_grad import SelectedRows
    rng = np.random.RandomState(0)
    ins, _ = _opt_case(rng, "sgd", (8,))
    assert zk.supports("sgd", ins)
    assert not zk.supports("lamb", ins)
    sr = SelectedRows(rows=np.zeros((1, 8), np.float32),
                      ids=np.array([0], np.int32))
    assert not zk.supports("sgd", {**ins, "Grad": [sr]})
    old = os.environ.pop("PADDLE_TPU_PALLAS_OPT", None)
    try:
        assert not zk.opt_kernel_enabled()
        os.environ["PADDLE_TPU_PALLAS_OPT"] = "1"
        assert zk.opt_kernel_enabled()
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_PALLAS_OPT", None)
        else:
            os.environ["PADDLE_TPU_PALLAS_OPT"] = old


@pytest.mark.slow  # ~50 s dp=2 subprocess; ci.py shards run it (the
# fast half of the contract — fused vs jitted rule, both layouts — is
# test_fused_update_bitwise_vs_jitted_rule above, and kernel_smoke.py
# re-pins it in CI)
def test_fused_zero_update_stages_dp2():
    """End-to-end `__zero_update__` parity on a dp=2 CPU mesh: 6 training
    steps of the tiny BERT at ZeRO stages 1/2/3 (stage 3 also @LAYERS
    rolled) with the fused kernel OFF then ON — loss series AND every
    persistable (params + moments + pow accumulators) bit-for-bit, the
    kernel-on arm actually funnelled through the kernel (monitor stat),
    and a kernel-on checkpoint continues bit-identically under a
    kernel-off program (and vice versa): checkpoints are portable in
    both directions."""
    code = """
import json, os, tempfile
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import monitor
from paddle_tpu.models import bert
from paddle_tpu.distributed import fleet
from paddle_tpu.testing import reset_programs

def build(stage, layer_scan=False):
    reset_programs(0)
    cfg = bert.BertConfig(vocab_size=256, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position=32, seq_len=16, hidden_dropout=0.0,
                          attention_dropout=0.0)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.sharding_stage = stage
    s.fuse_grad_size_in_mb = 0.02     # >= 3 buckets -> several updates
    s.layer_scan = layer_scan
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-3), s)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"input_ids": rng.randint(0, 256, (8, 16)).astype(np.int64),
            "mlm_labels": rng.randint(0, 256, (8, 16, 1)).astype(np.int64)}
    return exe, feed, loss

def steps(exe, feed, loss, n):
    prog = fluid.default_main_program()
    return [exe.run(program=prog, feed=feed,
                    fetch_list=[loss])[0].tobytes().hex()
            for _ in range(n)]

tmp = tempfile.mkdtemp()
out = {"mismatch": [], "fused_calls": {}}
for stage, rolled in ((1, False), (2, False), (3, False), (3, True)):
    tag = f"s{stage}{'r' if rolled else ''}"
    arms = {}
    for fused in (False, True):
        os.environ["PADDLE_TPU_PALLAS_OPT"] = "1" if fused else "0"
        monitor.stat_reset("executor.pallas_opt_fused")
        exe, feed, loss = build(stage, layer_scan=rolled)
        ls = steps(exe, feed, loss, 6)
        ck = os.path.join(tmp, f"{tag}_{int(fused)}")
        paddle.fluid.io.save_persistables(
            exe, ck, main_program=fluid.default_main_program())
        arms[fused] = {"losses": ls, "ck": ck,
                       "exe": exe, "feed": feed, "loss": loss,
                       "stat": monitor.stat_get("executor.pallas_opt_fused")}
    if arms[True]["losses"] != arms[False]["losses"]:
        out["mismatch"].append(f"{tag}: loss series")
    a = dict(np.load(os.path.join(arms[False]["ck"], "persistables.npz")))
    b = dict(np.load(os.path.join(arms[True]["ck"], "persistables.npz")))
    if sorted(a) != sorted(b):
        out["mismatch"].append(f"{tag}: persistable keys")
    else:
        for k in a:
            if a[k].tobytes() != b[k].tobytes():
                out["mismatch"].append(f"{tag}: {k}")
    out["fused_calls"][tag] = arms[True]["stat"]
    if stage == 1 and not rolled:
        # checkpoint portability, both directions: load the OTHER arm's
        # checkpoint and continue — series must stay identical
        cont = {}
        for fused in (False, True):
            os.environ["PADDLE_TPU_PALLAS_OPT"] = "1" if fused else "0"
            arm = arms[fused]
            paddle.fluid.io.load_persistables(
                arm["exe"], arms[not fused]["ck"],
                main_program=fluid.default_main_program())
            cont[fused] = steps(arm["exe"], arm["feed"], arm["loss"], 2)
        if cont[True] != cont[False]:
            out["mismatch"].append(f"{tag}: cross-checkpoint continue")
print(json.dumps(out))
"""
    r = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                       env=cpu_mesh_env(2), capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, f"subprocess failed:\n{r.stdout}\n{r.stderr}"
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["mismatch"] == [], out["mismatch"]
    # the fused arm really took the kernel funnel at every stage
    for tag, calls in out["fused_calls"].items():
        assert calls > 0, (tag, out["fused_calls"])
