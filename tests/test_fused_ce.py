"""fused_lm_head_ce: vocab-chunked streaming LM-head cross-entropy.

Parity: forward loss and BOTH gradients (hidden states and weight) must
match the dense matmul+softmax_with_cross_entropy pair to float
tolerance, with a chunk size that forces multiple scan steps AND a
ragged final chunk. Memory: the fused program's largest live tensor
must stay chunk-sized where the dense one materializes [B, S, V]
logits (asserted on optimized HLO — no hardware needed)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.testing import reset_programs


def _build_ce(fused, b, s, h, v, chunk=None, seed=0):
    """Tiny LM-ish program: trainable x-projection + head table, CE loss.
    Returns (exe, feed, loss, names of grads to fetch)."""
    reset_programs(seed=seed)
    feat = layers.data(name="feat", shape=[s, h], dtype="float32")
    label = layers.data(name="label", shape=[s, 1], dtype="int64")
    proj = layers.create_parameter([h, h], "float32", name="proj")
    w = layers.create_parameter([v, h], "float32", name="head_w")
    x = layers.matmul(feat, proj)
    if fused:
        loss_tok = layers.fused_lm_head_ce(x, w, label,
                                           chunk=chunk or 8192)
    else:
        logits = layers.matmul(x, w, transpose_y=True)
        loss_tok = layers.softmax_with_cross_entropy(logits, label)
    loss = layers.mean(loss_tok)
    paddle.optimizer.SGD(learning_rate=0.0).minimize(loss)  # grads only
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(7)
    feed = {"feat": rng.randn(b, s, h).astype(np.float32) * 0.3,
            "label": rng.randint(0, v, (b, s, 1)).astype(np.int64)}
    return exe, feed, loss


def _loss_and_grads(fused, chunk=None, v=37):
    exe, feed, loss = _build_ce(fused, b=2, s=5, h=16, v=v, chunk=chunk)
    gb = fluid.default_main_program().global_block()
    fetches = [loss.name, "proj@GRAD", "head_w@GRAD"]
    fetches = [f for f in fetches if gb.has_var(f)]
    return exe.run(feed=feed, fetch_list=fetches)


def test_fused_ce_matches_dense_loss_and_grads():
    # chunk 8 over v=37: 5 scan steps with a ragged 5-row final chunk
    dense = _loss_and_grads(fused=False)
    fused = _loss_and_grads(fused=True, chunk=8)
    assert len(dense) == len(fused) == 3
    for d, f in zip(dense, fused):
        np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                                   rtol=2e-5, atol=2e-6)


def test_fused_ce_single_chunk_matches():
    dense = _loss_and_grads(fused=False)
    fused = _loss_and_grads(fused=True, chunk=64)   # one chunk covers all
    np.testing.assert_allclose(np.asarray(fused[0]), np.asarray(dense[0]),
                               rtol=2e-5)


def test_gpt_auto_selects_fused_head():
    from paddle_tpu.models import gpt
    reset_programs(seed=0)
    cfg = gpt.GPTConfig(vocab_size=20000, hidden_size=32, num_layers=1,
                        num_heads=4, intermediate_size=64, max_position=16,
                        seq_len=16)
    gpt.build_lm_program(cfg)
    ops = [op.type for op in fluid.default_main_program()
           .global_block().ops]
    assert "fused_lm_head_ce" in ops
    reset_programs(seed=0)
    cfg.vocab_size = 512
    gpt.build_lm_program(cfg)
    ops = [op.type for op in fluid.default_main_program()
           .global_block().ops]
    assert "fused_lm_head_ce" not in ops
    assert "softmax_with_cross_entropy" in ops


def test_fused_ce_largest_live_tensor_is_bounded():
    """Compile both variants at a vocab where [B,S,V] logits dominate and
    compare the LARGEST tensor in the optimized HLO (memory_analysis
    reports no temp bytes on the CPU backend, so assert on structure:
    the fused program must never materialize a vocab-sized tensor)."""
    import re

    DT = {"f32": 4, "f64": 8, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
          "s32": 4, "u32": 4, "pred": 1, "s8": 1, "u8": 1}

    def largest_tensor_bytes(fused):
        exe, feed, loss = _build_ce(fused, b=4, s=64, h=64, v=16384,
                                    chunk=2048, seed=0)
        # the public compile-stats surface; no executor internals
        txt = exe.compiled_hlo(feed, [loss])
        biggest = 0
        for m in re.finditer(r"= (\w+)\[([\d,]+)\]", txt):
            dt, shape = m.groups()
            n = 1
            for d in shape.split(","):
                n *= int(d)
            biggest = max(biggest, n * DT.get(dt, 4))
        return biggest

    dense = largest_tensor_bytes(False)
    fused = largest_tensor_bytes(True)
    # dense materializes f32[4,64,16384] = 16.8 MB logits; the fused
    # program's biggest tensor is a [4,64,2048] chunk (2 MB) or the
    # [16384,64] weight (4.2 MB). A surviving vocab-x-seq-sized tensor
    # means the streaming structure broke.
    assert dense >= 4 * 64 * 16384 * 4, dense       # sanity: logits seen
    assert fused * 3 < dense, (dense, fused)


def test_fused_ce_under_amp_bf16():
    """fused_lm_head_ce is AMP white-listed (amp/auto_cast.py): bf16-cast
    operands with f32 einsum accumulation must track the f32 loss within
    bf16 tolerance — the GPT bench row runs exactly this combination."""
    exe, feed, loss = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    ref, = exe.run(feed=feed, fetch_list=[loss])
    exe2, feed2, loss2 = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    fluid.default_main_program()._amp = True        # what strategy.amp sets
    amp, = exe2.run(feed=feed2, fetch_list=[loss2])
    np.testing.assert_allclose(np.asarray(amp), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_fused_ce_hv_layout_with_bias_matches_fc():
    """BERT-head shape: fc([H,V] weight + [V] bias) + CE vs the fused op
    with w_layout='hv' — loss and all three grads must match."""
    def build(fused):
        reset_programs(seed=3)
        b, s, h, v = 2, 5, 16, 37
        feat = layers.data(name="feat", shape=[s, h], dtype="float32")
        label = layers.data(name="label", shape=[s, 1], dtype="int64")
        w = layers.create_parameter([h, v], "float32", name="head_hv")
        bia = layers.create_parameter([v], "float32", name="head_b",
                                      is_bias=True)
        if fused:
            loss_tok = layers.fused_lm_head_ce(feat, w, label, chunk=8,
                                               bias=bia, w_layout="hv")
        else:
            logits = layers.elementwise_add(layers.matmul(feat, w), bia)
            loss_tok = layers.softmax_with_cross_entropy(logits, label)
        loss = layers.mean(loss_tok)
        paddle.optimizer.SGD(learning_rate=0.0).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(5)
        feed = {"feat": rng.randn(b, s, h).astype(np.float32) * 0.3,
                "label": rng.randint(0, v, (b, s, 1)).astype(np.int64)}
        # bias init is 0: nudge it so its grad path is actually exercised
        from paddle_tpu.framework.scope import global_scope
        import jax.numpy as jnp
        global_scope().set("head_b", jnp.asarray(
            rng.randn(v).astype(np.float32) * 0.1))
        return exe.run(feed=feed, fetch_list=[
            loss.name, "head_hv@GRAD", "head_b@GRAD"])

    dense = build(False)
    fused = build(True)
    # tolerance note: when this file is run directly under the TPU
    # plugin preload (not through ci.py's sanitized CPU-mesh env), it
    # executes on the real chip, where f32 matmuls default to bf16-grade
    # MXU passes — measured 1.2e-5 abs / ~1% rel deviation between the
    # chunked and dense groupings, vs 1.5e-8 on CPU. Real math bugs
    # produce O(1) relative errors, so 5% rel still catches them on
    # either backend.
    for d, f in zip(dense, fused):
        np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                                   rtol=5e-2, atol=1e-4)


def test_bert_auto_selects_fused_head():
    """BERT auto rule: fused MLM head only at long seq AND real vocab —
    at the short-seq bench geometry the dense head fits HBM and the
    fused backward's recompute would cost ~7% model FLOPs for nothing."""
    from paddle_tpu.models import bert

    def head_ops(cfg):
        reset_programs(seed=0)
        bert.build_pretrain_program(cfg)
        return [op.type for op in fluid.default_main_program()
                .global_block().ops]

    long_cfg = bert.BertConfig(vocab_size=20000, hidden_size=32,
                               num_layers=1, num_heads=4,
                               intermediate_size=64, max_position=512,
                               seq_len=512)
    assert "fused_lm_head_ce" in head_ops(long_cfg)
    short_cfg = bert.BertConfig(vocab_size=20000, hidden_size=32,
                                num_layers=1, num_heads=4,
                                intermediate_size=64, max_position=16,
                                seq_len=16)
    assert "fused_lm_head_ce" not in head_ops(short_cfg)
    short_cfg.fused_mlm_head = True         # explicit force wins
    assert "fused_lm_head_ce" in head_ops(short_cfg)


def test_fused_ce_out_of_range_label_is_nan():
    """Labels outside [0, V) have no implemented ignore semantics: the op
    yields NaN for that token (loud), per the documented contract."""
    exe, feed, loss = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    feed = dict(feed)
    bad = feed["label"].copy()
    bad[0, 0, 0] = -1
    bad[1, 2, 0] = 37
    feed["label"] = bad
    gb = fluid.default_main_program().global_block()
    fetches = [loss.name] + [f for f in ("proj@GRAD", "head_w@GRAD")
                             if gb.has_var(f)]
    vals = exe.run(feed=feed, fetch_list=fetches)
    assert np.isnan(np.asarray(vals[0])), \
        "out-of-range label must surface NaN loss"
    # the custom-VJP backward must be loud too: a finite gradient with
    # the label term silently missing would corrupt training
    for name, g in zip(fetches[1:], vals[1:]):
        assert np.isnan(np.asarray(g)).any(), \
            f"{name} must carry NaN for the invalid token"


def _ignore_ce_build(fused, ignore_index=-1):
    """Dense-vs-fused builder whose labels include ignore_index tokens."""
    reset_programs(seed=11)
    b, s, h, v = 2, 5, 16, 37
    feat = layers.data(name="feat", shape=[s, h], dtype="float32")
    label = layers.data(name="label", shape=[s, 1], dtype="int64")
    proj = layers.create_parameter([h, h], "float32", name="proj")
    w = layers.create_parameter([v, h], "float32", name="head_w")
    x = layers.matmul(feat, proj)
    if fused:
        loss_tok = layers.fused_lm_head_ce(x, w, label, chunk=8,
                                           ignore_index=ignore_index)
    else:
        logits = layers.matmul(x, w, transpose_y=True)
        loss_tok = layers.softmax_with_cross_entropy(
            logits, label, ignore_index=ignore_index)
    loss = layers.mean(loss_tok)
    paddle.optimizer.SGD(learning_rate=0.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(13)
    lab = rng.randint(0, v, (b, s, 1)).astype(np.int64)
    lab[0, :3, 0] = ignore_index                 # ignored tokens
    feed = {"feat": rng.randn(b, s, h).astype(np.float32) * 0.3,
            "label": lab}
    return exe, feed, loss_tok


def test_sce_ignore_index_zeroes_loss_and_grads():
    """softmax_with_cross_entropy honors ignore_index (it used to accept
    and silently drop the kwarg): ignored tokens get zero loss and
    contribute nothing to the gradients."""
    exe, feed, loss_tok = _ignore_ce_build(fused=False)
    lt, gp, gw = exe.run(feed=feed,
                         fetch_list=[loss_tok.name, "proj@GRAD",
                                     "head_w@GRAD"])
    assert np.all(np.asarray(lt)[0, :3] == 0.0)
    assert np.all(np.asarray(lt)[0, 3:] > 0.0)
    assert np.isfinite(np.asarray(gp)).all()
    assert np.isfinite(np.asarray(gw)).all()
    # zero-grad check: an all-ignored batch must produce exactly zero
    feed_all = dict(feed)
    feed_all["label"] = np.full_like(feed["label"], -1)
    lt2, gp2, gw2 = exe.run(feed=feed_all,
                            fetch_list=[loss_tok.name, "proj@GRAD",
                                        "head_w@GRAD"])
    assert np.all(np.asarray(lt2) == 0.0)
    np.testing.assert_array_equal(np.asarray(gp2), 0.0)
    np.testing.assert_array_equal(np.asarray(gw2), 0.0)


def test_fused_ce_ignore_index_matches_dense():
    """The dense/fused auto-switch must not change ignore-label behavior
    (ADVICE #1): with the SAME ignore_index, per-token losses and both
    gradients match to float tolerance."""
    dense_exe, feed, dense_tok = _ignore_ce_build(fused=False)
    d = dense_exe.run(feed=feed, fetch_list=[dense_tok.name, "proj@GRAD",
                                             "head_w@GRAD"])
    fused_exe, feed_f, fused_tok = _ignore_ce_build(fused=True)
    f = fused_exe.run(feed=feed_f, fetch_list=[fused_tok.name, "proj@GRAD",
                                               "head_w@GRAD"])
    for dv, fv in zip(d, f):
        np.testing.assert_allclose(np.asarray(fv), np.asarray(dv),
                                   rtol=2e-5, atol=2e-6)


def test_bert_fused_auto_select_gated_off_under_tp_vocab_sharding():
    """With an active tp>1 mesh (whose rules vocab-shard mlm_head_w,
    bert.tp_sharding_rules P(None,'tp')), the fused-MLM-head AUTO-select
    stays dense — the chunked scan would force GSPMD to regather the
    sharded weight per chunk (ADVICE #2). Forcing fused_mlm_head=True
    still wins; a dp-only mesh leaves the auto-select on."""
    import jax
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.mesh import build_mesh, get_mesh, set_mesh

    def head_ops(cfg):
        reset_programs(seed=0)
        bert.build_pretrain_program(cfg)
        return [op.type for op in fluid.default_main_program()
                .global_block().ops]

    cfg = bert.BertConfig(vocab_size=16384, hidden_size=16, num_layers=1,
                          num_heads=2, intermediate_size=32,
                          max_position=512, seq_len=512,
                          hidden_dropout=0.0, attention_dropout=0.0)
    old = get_mesh()
    try:
        set_mesh(build_mesh(tp=2, devices=jax.devices()[:2]))
        ops = head_ops(cfg)
        assert "fused_lm_head_ce" not in ops
        assert "softmax_with_cross_entropy" in ops
        cfg.fused_mlm_head = True               # explicit force wins
        assert "fused_lm_head_ce" in head_ops(cfg)
        cfg.fused_mlm_head = None
        set_mesh(build_mesh(dp=2, devices=jax.devices()[:2]))
        assert "fused_lm_head_ce" in head_ops(cfg)
    finally:
        set_mesh(old)


def test_tp_fused_head_build_then_init_warns():
    """The auto-gate reads the mesh at BUILD time, so the canonical
    build-then-fleet.init order slips past it; minimize must then warn
    that the auto-selected fused head will be regathered under the tp
    vocab-sharding rules (a user-FORCED fused head stays silent)."""
    import warnings as _warnings

    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.mesh import get_mesh, set_mesh

    old = get_mesh()
    try:
        set_mesh(None)                      # build BEFORE any mesh exists
        cfg = bert.BertConfig(vocab_size=16384, hidden_size=16,
                              num_layers=1, num_heads=2,
                              intermediate_size=32, max_position=512,
                              seq_len=512, hidden_dropout=0.0,
                              attention_dropout=0.0)

        def minimize(forced):
            reset_programs(seed=0)
            cfg.fused_mlm_head = True if forced else None
            ids, labels, loss = bert.build_pretrain_program(cfg)
            ops = [op.type for op in fluid.default_main_program()
                   .global_block().ops]
            assert "fused_lm_head_ce" in ops    # gate missed: no mesh yet
            fleet.init(is_collective=True)
            s = fleet.DistributedStrategy(
                tensor_parallel_degree=2,
                tensor_parallel_rules=bert.tp_sharding_rules())
            opt = fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.1), s)
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                opt.minimize(loss)
            return [w for w in caught
                    if "regather" in str(w.message)]

        assert minimize(forced=False), "auto-selected head must warn"
        assert not minimize(forced=True), "forced head must stay silent"
    finally:
        set_mesh(old)


@pytest.mark.slow
def test_tp_fused_head_collective_audit():
    """The collective evidence behind the tp auto-gate (ADVICE #2): with a
    vocab-sharded head weight (P(None,'tp')) and a MULTI-chunk fused head
    (chunk < V/shards), GSPMD regathers weight-sized data — all-gather
    bytes at least the full head weight — while the dense vocab-parallel
    head needs NO all-gather of the weight at all (small activation
    all-reduces only). Audited on optimized HLO through the public
    Executor.compiled_hlo. (At a single-chunk geometry, chunk >= V, the
    scan degenerates and GSPMD keeps the weight sharded — the auto-select
    thresholds guarantee >= 2 chunks, so the gate targets exactly the
    regathering regime.)"""
    import re

    import jax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel import DistConfig, attach, build_mesh
    from paddle_tpu.parallel.mesh import ShardingRules

    DT = {"f32": 4, "f64": 8, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
          "s32": 4, "u32": 4, "pred": 1, "s8": 1, "u8": 1}

    def all_gather_bytes(txt):
        total = 0
        for line in txt.splitlines():
            m = re.search(r"%\S+ = (.*?) all-gather(?:-start)?\(", line)
            if not m:
                continue
            for dm in re.finditer(r"(\w+)\[([\d,]*)\]", m.group(1)):
                dt, shape = dm.groups()
                n = 1
                for d in shape.split(","):
                    if d:
                        n *= int(d)
                total += n * DT.get(dt, 4)
        return total

    b, s, h, v = 4, 32, 32, 4096

    def compile_head(fused):
        reset_programs(seed=0)
        feat = layers.data(name="feat", shape=[s, h], dtype="float32")
        label = layers.data(name="label", shape=[s, 1], dtype="int64")
        w = layers.create_parameter([h, v], "float32", name="mlm_head_w")
        if fused:
            loss_tok = layers.fused_lm_head_ce(feat, w, label, chunk=512,
                                               w_layout="hv")
        else:
            logits = layers.matmul(feat, w)
            loss_tok = layers.softmax_with_cross_entropy(logits, label)
        loss = layers.mean(loss_tok)
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        mesh = build_mesh(tp=2, devices=jax.devices()[:2])
        attach(fluid.default_main_program(),
               DistConfig(mesh=mesh, param_rules=ShardingRules(
                   [(r"^mlm_head_w$", P(None, "tp"))])))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        feed = {"feat": np.zeros((b, s, h), np.float32),
                "label": np.zeros((b, s, 1), np.int64)}
        return exe.compiled_hlo(feed, [loss])

    w_bytes = h * v * 4
    fused_ag = all_gather_bytes(compile_head(True))
    dense_ag = all_gather_bytes(compile_head(False))
    assert fused_ag >= w_bytes, (fused_ag, w_bytes)     # the regather
    assert dense_ag < w_bytes, (dense_ag, w_bytes)      # the gated path
