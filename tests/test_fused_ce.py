"""fused_lm_head_ce: vocab-chunked streaming LM-head cross-entropy.

Parity: forward loss and BOTH gradients (hidden states and weight) must
match the dense matmul+softmax_with_cross_entropy pair to float
tolerance, with a chunk size that forces multiple scan steps AND a
ragged final chunk. Memory: the fused program's largest live tensor
must stay chunk-sized where the dense one materializes [B, S, V]
logits (asserted on optimized HLO — no hardware needed)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.testing import reset_programs


def _build_ce(fused, b, s, h, v, chunk=None, seed=0):
    """Tiny LM-ish program: trainable x-projection + head table, CE loss.
    Returns (exe, feed, loss, names of grads to fetch)."""
    reset_programs(seed=seed)
    feat = layers.data(name="feat", shape=[s, h], dtype="float32")
    label = layers.data(name="label", shape=[s, 1], dtype="int64")
    proj = layers.create_parameter([h, h], "float32", name="proj")
    w = layers.create_parameter([v, h], "float32", name="head_w")
    x = layers.matmul(feat, proj)
    if fused:
        loss_tok = layers.fused_lm_head_ce(x, w, label,
                                           chunk=chunk or 8192)
    else:
        logits = layers.matmul(x, w, transpose_y=True)
        loss_tok = layers.softmax_with_cross_entropy(logits, label)
    loss = layers.mean(loss_tok)
    paddle.optimizer.SGD(learning_rate=0.0).minimize(loss)  # grads only
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(7)
    feed = {"feat": rng.randn(b, s, h).astype(np.float32) * 0.3,
            "label": rng.randint(0, v, (b, s, 1)).astype(np.int64)}
    return exe, feed, loss


def _loss_and_grads(fused, chunk=None, v=37):
    exe, feed, loss = _build_ce(fused, b=2, s=5, h=16, v=v, chunk=chunk)
    gb = fluid.default_main_program().global_block()
    fetches = [loss.name, "proj@GRAD", "head_w@GRAD"]
    fetches = [f for f in fetches if gb.has_var(f)]
    return exe.run(feed=feed, fetch_list=fetches)


def test_fused_ce_matches_dense_loss_and_grads():
    # chunk 8 over v=37: 5 scan steps with a ragged 5-row final chunk
    dense = _loss_and_grads(fused=False)
    fused = _loss_and_grads(fused=True, chunk=8)
    assert len(dense) == len(fused) == 3
    for d, f in zip(dense, fused):
        np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                                   rtol=2e-5, atol=2e-6)


def test_fused_ce_single_chunk_matches():
    dense = _loss_and_grads(fused=False)
    fused = _loss_and_grads(fused=True, chunk=64)   # one chunk covers all
    np.testing.assert_allclose(np.asarray(fused[0]), np.asarray(dense[0]),
                               rtol=2e-5)


def test_gpt_auto_selects_fused_head():
    from paddle_tpu.models import gpt
    reset_programs(seed=0)
    cfg = gpt.GPTConfig(vocab_size=20000, hidden_size=32, num_layers=1,
                        num_heads=4, intermediate_size=64, max_position=16,
                        seq_len=16)
    gpt.build_lm_program(cfg)
    ops = [op.type for op in fluid.default_main_program()
           .global_block().ops]
    assert "fused_lm_head_ce" in ops
    reset_programs(seed=0)
    cfg.vocab_size = 512
    gpt.build_lm_program(cfg)
    ops = [op.type for op in fluid.default_main_program()
           .global_block().ops]
    assert "fused_lm_head_ce" not in ops
    assert "softmax_with_cross_entropy" in ops


def test_fused_ce_largest_live_tensor_is_bounded():
    """Compile both variants at a vocab where [B,S,V] logits dominate and
    compare the LARGEST tensor in the optimized HLO (memory_analysis
    reports no temp bytes on the CPU backend, so assert on structure:
    the fused program must never materialize a vocab-sized tensor)."""
    import re

    import jax

    DT = {"f32": 4, "f64": 8, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
          "s32": 4, "u32": 4, "pred": 1, "s8": 1, "u8": 1}

    def largest_tensor_bytes(fused):
        exe, feed, loss = _build_ce(fused, b=4, s=64, h=64, v=16384,
                                    chunk=2048, seed=0)
        exe.run(feed=feed, fetch_list=[loss])       # compile via executor
        cb = list(exe._cache.values())[-1]
        from paddle_tpu.framework.scope import global_scope
        import jax.numpy as jnp
        scope = global_scope()
        txt = cb.jitted.lower(
            {n: scope.find(n) for n in cb.mut_names},
            {n: scope.find(n) for n in cb.ro_names},
            {k: jnp.asarray(v) for k, v in feed.items()},
            jax.random.key(0)).compile().as_text()
        biggest = 0
        for m in re.finditer(r"= (\w+)\[([\d,]+)\]", txt):
            dt, shape = m.groups()
            n = 1
            for d in shape.split(","):
                n *= int(d)
            biggest = max(biggest, n * DT.get(dt, 4))
        return biggest

    dense = largest_tensor_bytes(False)
    fused = largest_tensor_bytes(True)
    # dense materializes f32[4,64,16384] = 16.8 MB logits; the fused
    # program's biggest tensor is a [4,64,2048] chunk (2 MB) or the
    # [16384,64] weight (4.2 MB). A surviving vocab-x-seq-sized tensor
    # means the streaming structure broke.
    assert dense >= 4 * 64 * 16384 * 4, dense       # sanity: logits seen
    assert fused * 3 < dense, (dense, fused)


def test_fused_ce_under_amp_bf16():
    """fused_lm_head_ce is AMP white-listed (amp/auto_cast.py): bf16-cast
    operands with f32 einsum accumulation must track the f32 loss within
    bf16 tolerance — the GPT bench row runs exactly this combination."""
    exe, feed, loss = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    ref, = exe.run(feed=feed, fetch_list=[loss])
    exe2, feed2, loss2 = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    fluid.default_main_program()._amp = True        # what strategy.amp sets
    amp, = exe2.run(feed=feed2, fetch_list=[loss2])
    np.testing.assert_allclose(np.asarray(amp), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_fused_ce_hv_layout_with_bias_matches_fc():
    """BERT-head shape: fc([H,V] weight + [V] bias) + CE vs the fused op
    with w_layout='hv' — loss and all three grads must match."""
    def build(fused):
        reset_programs(seed=3)
        b, s, h, v = 2, 5, 16, 37
        feat = layers.data(name="feat", shape=[s, h], dtype="float32")
        label = layers.data(name="label", shape=[s, 1], dtype="int64")
        w = layers.create_parameter([h, v], "float32", name="head_hv")
        bia = layers.create_parameter([v], "float32", name="head_b",
                                      is_bias=True)
        if fused:
            loss_tok = layers.fused_lm_head_ce(feat, w, label, chunk=8,
                                               bias=bia, w_layout="hv")
        else:
            logits = layers.elementwise_add(layers.matmul(feat, w), bia)
            loss_tok = layers.softmax_with_cross_entropy(logits, label)
        loss = layers.mean(loss_tok)
        paddle.optimizer.SGD(learning_rate=0.0).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(5)
        feed = {"feat": rng.randn(b, s, h).astype(np.float32) * 0.3,
                "label": rng.randint(0, v, (b, s, 1)).astype(np.int64)}
        # bias init is 0: nudge it so its grad path is actually exercised
        from paddle_tpu.framework.scope import global_scope
        import jax.numpy as jnp
        global_scope().set("head_b", jnp.asarray(
            rng.randn(v).astype(np.float32) * 0.1))
        return exe.run(feed=feed, fetch_list=[
            loss.name, "head_hv@GRAD", "head_b@GRAD"])

    dense = build(False)
    fused = build(True)
    # tolerance note: when this file is run directly under the TPU
    # plugin preload (not through ci.py's sanitized CPU-mesh env), it
    # executes on the real chip, where f32 matmuls default to bf16-grade
    # MXU passes — measured 1.2e-5 abs / ~1% rel deviation between the
    # chunked and dense groupings, vs 1.5e-8 on CPU. Real math bugs
    # produce O(1) relative errors, so 5% rel still catches them on
    # either backend.
    for d, f in zip(dense, fused):
        np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                                   rtol=5e-2, atol=1e-4)


def test_bert_auto_selects_fused_head():
    """BERT auto rule: fused MLM head only at long seq AND real vocab —
    at the short-seq bench geometry the dense head fits HBM and the
    fused backward's recompute would cost ~7% model FLOPs for nothing."""
    from paddle_tpu.models import bert

    def head_ops(cfg):
        reset_programs(seed=0)
        bert.build_pretrain_program(cfg)
        return [op.type for op in fluid.default_main_program()
                .global_block().ops]

    long_cfg = bert.BertConfig(vocab_size=20000, hidden_size=32,
                               num_layers=1, num_heads=4,
                               intermediate_size=64, max_position=512,
                               seq_len=512)
    assert "fused_lm_head_ce" in head_ops(long_cfg)
    short_cfg = bert.BertConfig(vocab_size=20000, hidden_size=32,
                                num_layers=1, num_heads=4,
                                intermediate_size=64, max_position=16,
                                seq_len=16)
    assert "fused_lm_head_ce" not in head_ops(short_cfg)
    short_cfg.fused_mlm_head = True         # explicit force wins
    assert "fused_lm_head_ce" in head_ops(short_cfg)


def test_fused_ce_out_of_range_label_is_nan():
    """Labels outside [0, V) have no implemented ignore semantics: the op
    yields NaN for that token (loud), per the documented contract."""
    exe, feed, loss = _build_ce(True, b=2, s=5, h=16, v=37, chunk=8)
    feed = dict(feed)
    bad = feed["label"].copy()
    bad[0, 0, 0] = -1
    bad[1, 2, 0] = 37
    feed["label"] = bad
    gb = fluid.default_main_program().global_block()
    fetches = [loss.name] + [f for f in ("proj@GRAD", "head_w@GRAD")
                             if gb.has_var(f)]
    vals = exe.run(feed=feed, fetch_list=fetches)
    assert np.isnan(np.asarray(vals[0])), \
        "out-of-range label must surface NaN loss"
    # the custom-VJP backward must be loud too: a finite gradient with
    # the label term silently missing would corrupt training
    for name, g in zip(fetches[1:], vals[1:]):
        assert np.isnan(np.asarray(g)).any(), \
            f"{name} must carry NaN for the invalid token"
