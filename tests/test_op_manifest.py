"""Mechanical audit of docs/op_manifest.json (the coverage claim artifact).

Reference counterpart: the REGISTER_OPERATOR surface under
paddle/fluid/operators. Every name the reference registers must be
classified registered | subsumed | cut | n/a, and every 'registered' claim
must hold against the live runtime registry."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(ROOT, "docs", "op_manifest.json")


def test_manifest_exists_and_classifies_everything():
    with open(MANIFEST) as f:
        doc = json.load(f)
    assert doc["ops"], "empty manifest"
    statuses = {e["status"] for e in doc["ops"].values()}
    assert "UNCLASSIFIED" not in statuses
    assert statuses <= {"registered", "subsumed", "cut", "n/a"}
    # every subsumed entry names its mechanism; cut/n-a entries say why
    for n, e in doc["ops"].items():
        if e["status"] == "subsumed":
            assert e.get("via"), f"{n}: subsumed without a mechanism"
        if e["status"] in ("cut", "n/a"):
            assert e.get("why"), f"{n}: {e['status']} without a reason"


def test_manifest_check_passes_against_live_registry():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "op_manifest.py"),
         "--check"],
        capture_output=True, text=True, timeout=300, cwd=ROOT)
    assert r.returncode == 0, r.stdout + r.stderr


def test_registered_claims_hold():
    with open(MANIFEST) as f:
        doc = json.load(f)
    import paddle_tpu  # noqa: F401
    import paddle_tpu.contrib.slim.quantization  # noqa: F401
    import paddle_tpu.distributed.ps_pass  # noqa: F401
    import paddle_tpu.parallel.transforms  # noqa: F401
    from paddle_tpu.ops import registry
    missing = [n for n, e in doc["ops"].items()
               if e["status"] == "registered" and n not in registry._REGISTRY]
    assert not missing, f"manifest over-claims: {missing}"
