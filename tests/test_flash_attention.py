"""Flash attention kernel numerics: forward and backward vs dense reference.

Runs the pallas kernels in interpreter mode on CPU (the same code path
compiles via Mosaic on real TPU; bench.py exercises that). Mirrors the
reference's fused-attention tests (test_fused_multihead_matmul_op.py
pattern: dense numpy reference, tight tolerances).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense_ref(q, k, v, scale, causal):
    s = jnp.einsum("bnqd,bnkd->bnqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sl = q.shape[2]
        mask = jnp.tril(jnp.ones((sl, sl), bool))[None, None]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq,block_q,block_k", [
    (256, 128, 128),   # multiple blocks both ways
    (128, 256, 512),   # blocks clamped to seq
    (512, 256, 128),   # k blocks < q blocks and vice versa
])
def test_flash_fwd_bwd_matches_dense(causal, seq, block_q, block_k):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    b, nh, hd = 2, 2, 64
    q = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    k = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    v = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    do = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    scale = 1.0 / np.sqrt(hd)

    out = flash_attention(q, k, v, scale, causal, block_q, block_k)
    ref = _dense_ref(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, scale, causal,
                                        block_q, block_k), do)

    def loss_ref(q, k, v):
        return jnp.vdot(_dense_ref(q, k, v, scale, causal), do)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal}, seq={seq})")


def test_flash_bf16_grads_finite():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 128, 128)
                       .astype(jnp.float32))

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g, dtype=np.float32)).all()
        assert g.dtype == jnp.bfloat16
