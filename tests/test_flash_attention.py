"""Flash attention kernel numerics: forward and backward vs dense reference.

Runs the pallas kernels in interpreter mode on CPU (the same code path
compiles via Mosaic on real TPU; bench.py exercises that). Mirrors the
reference's fused-attention tests (test_fused_multihead_matmul_op.py
pattern: dense numpy reference, tight tolerances).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _dense_ref(q, k, v, scale, causal):
    s = jnp.einsum("bnqd,bnkd->bnqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sl = q.shape[2]
        mask = jnp.tril(jnp.ones((sl, sl), bool))[None, None]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq,block_q,block_k", [
    (256, 128, 128),   # multiple blocks both ways
    (128, 256, 512),   # blocks clamped to seq
    (512, 256, 128),   # k blocks < q blocks and vice versa
])
def test_flash_fwd_bwd_matches_dense(causal, seq, block_q, block_k):
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    b, nh, hd = 2, 2, 64
    q = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    k = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    v = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    do = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    scale = 1.0 / np.sqrt(hd)

    out = flash_attention(q, k, v, scale, causal, block_q, block_k)
    ref = _dense_ref(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, scale, causal,
                                        block_q, block_k), do)

    def loss_ref(q, k, v):
        return jnp.vdot(_dense_ref(q, k, v, scale, causal), do)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal}, seq={seq})")


def test_flash_dropout_mask_semantics():
    """v = I recovers the dropped prob matrix: check drop rate, upscale
    factor, determinism per seed, and dropout=0 == plain path."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    S = hd = 128
    rate = 0.1
    q = jnp.asarray(rng.randn(1, 2, S, hd).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(1, 2, S, hd).astype(np.float32)) * 0.3
    v_eye = jnp.broadcast_to(jnp.eye(S, dtype=jnp.float32), (1, 2, S, S))

    out = flash_attention(q, k, v_eye, 1.0, False, 128, 128,
                          dropout=rate, seed=42)
    pd = np.asarray(out)
    probs = np.asarray(jax.nn.softmax(
        jnp.einsum("bnqd,bnkd->bnqk", q, k), axis=-1))
    mask = pd != 0
    assert abs((1 - mask.mean()) - rate) < 0.02, "drop fraction off"
    ratio = pd[mask] / probs[mask]
    np.testing.assert_allclose(ratio, 1.0 / (1 - rate), rtol=1e-5)

    out2 = flash_attention(q, k, v_eye, 1.0, False, 128, 128,
                           dropout=rate, seed=42)
    assert bool(jnp.all(out == out2)), "same seed must reproduce"
    out3 = flash_attention(q, k, v_eye, 1.0, False, 128, 128,
                           dropout=rate, seed=43)
    assert bool(jnp.any(out != out3)), "different seed must differ"
    plain = flash_attention(q, k, v_eye, 1.0, False, 128, 128)
    zero = flash_attention(q, k, v_eye, 1.0, False, 128, 128,
                           dropout=0.0, seed=7)
    assert bool(jnp.all(plain == zero))


def test_flash_dropout_grads_match_dense_with_same_mask():
    """The in-kernel mask depends only on (seed, head, positions), so recover
    it via uniform probs + v=I, then check fwd and all three grads against a
    dense implementation using that exact mask."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(1)
    S = hd = 128
    rate, seed = 0.15, 7
    q = jnp.asarray(rng.randn(2, 2, S, hd).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(2, 2, S, hd).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(2, 2, S, hd).astype(np.float32))
    v_eye = jnp.broadcast_to(jnp.eye(S, dtype=jnp.float32), (2, 2, S, S))

    pd = flash_attention(jnp.zeros_like(q), jnp.zeros_like(k), v_eye,
                         1.0, False, 128, 128, dropout=rate, seed=seed)
    keep = jnp.asarray(np.asarray(pd) != 0)

    def dense(q, k, v):
        p = jax.nn.softmax(
            jnp.einsum("bnqd,bnkd->bnqk", q, k) * (hd ** -0.5), axis=-1)
        return jnp.einsum("bnqk,bnkd->bnqd",
                          jnp.where(keep, p / (1 - rate), 0.0), v)

    def flash(q, k, v):
        return flash_attention(q, k, v, None, False, 128, 128,
                               dropout=rate, seed=seed)

    cot = jnp.asarray(rng.randn(2, 2, S, hd).astype(np.float32))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(dense(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.vdot(flash(*a), cot), (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.vdot(dense(*a), cot), (0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("mask_shape", [
    (1, 1, 1, 256),   # shared key bias
    (2, 1, 1, 256),   # per-batch key padding (the padded-BERT case)
    (1, 2, 256, 256), # per-head full bias (ALiBi-style), batch-broadcast
    (2, 2, 256, 256), # distinct per (batch, head)
])
def test_flash_masked_matches_dense(mask_shape):
    """Additive mask applied in-kernel across fwd + both bwd kernels, for
    every head→mask broadcast layout the normalizer distinguishes."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(3)
    b, nh, seq, hd = 2, 2, 256, 64
    q = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    k = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    v = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    do = jnp.asarray(rng.randn(b, nh, seq, hd).astype(np.float32))
    scale = 1.0 / np.sqrt(hd)
    # mix of -1e9 "pad" entries and small finite biases
    bias = rng.randn(*mask_shape).astype(np.float32)
    pad = (rng.rand(*mask_shape) < 0.25) * -1e9
    mask = jnp.asarray(bias + pad.astype(np.float32))

    def dense(q, k, v):
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k) * scale + mask
        return jnp.einsum("bnqk,bnkd->bnqd", jax.nn.softmax(s, -1), v)

    def flash(q, k, v):
        return flash_attention(q, k, v, scale, False, 128, 128, mask=mask)

    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(dense(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.vdot(flash(*a), do), (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.vdot(dense(*a), do), (0, 1, 2))(q, k, v)
    for a, bb, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} ({mask_shape})")


def test_flash_mask_dropout_causal_combined():
    """The round-4 target path: padding mask + dropout + causal, all
    in-kernel at once. Recover the dropout keep-mask via v=I then compare
    against a dense implementation using mask, causal triangle and that
    exact keep pattern."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(5)
    b, nh, S, hd = 2, 2, 128, 128
    rate, seed = 0.1, 11
    q = jnp.asarray(rng.randn(b, nh, S, hd).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(b, nh, S, hd).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(b, nh, S, hd).astype(np.float32))
    v_eye = jnp.broadcast_to(jnp.eye(S, dtype=jnp.float32), (b, nh, S, S))
    # pad out the last 32 keys of example 1
    pad = np.zeros((b, 1, 1, S), np.float32)
    pad[1, :, :, S - 32:] = -1e9
    mask = jnp.asarray(pad)

    pd = flash_attention(jnp.zeros_like(q), jnp.zeros_like(k), v_eye,
                         1.0, False, 128, 128, dropout=rate, seed=seed)
    keep = jnp.asarray(np.asarray(pd) != 0)

    tri = jnp.tril(jnp.ones((S, S), bool))[None, None]

    def dense(q, k, v):
        s = jnp.einsum("bnqd,bnkd->bnqk", q, k) * (hd ** -0.5) + mask
        s = jnp.where(tri, s, -jnp.inf)
        p = jax.nn.softmax(s, -1)
        return jnp.einsum("bnqk,bnkd->bnqd",
                          jnp.where(keep, p / (1 - rate), 0.0), v)

    def flash(q, k, v):
        return flash_attention(q, k, v, None, True, 128, 128,
                               dropout=rate, seed=seed, mask=mask)

    cot = jnp.asarray(rng.randn(b, nh, S, hd).astype(np.float32))
    np.testing.assert_allclose(np.asarray(flash(q, k, v)),
                               np.asarray(dense(q, k, v)),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.vdot(flash(*a), cot), (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.vdot(dense(*a), cot), (0, 1, 2))(q, k, v)
    for a, bb, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_flash_int_mask_is_cast():
    """An int additive mask must not poison the bwd cotangent pytree."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 1, 128, 64).astype(np.float32))
    mask = jnp.asarray(
        (rng.rand(1, 1, 1, 128) < 0.3) * np.int32(-10 ** 9))
    out = flash_attention(q, q, q, None, False, 128, 128, mask=mask)
    g = jax.grad(lambda a: jnp.sum(flash_attention(
        a, a, a, None, False, 128, 128, mask=mask)))(q)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(g)).all()


def test_flash_bf16_grads_finite():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 2, 256, 64)).astype(jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True, 128, 128)
                       .astype(jnp.float32))

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g, dtype=np.float32)).all()
        assert g.dtype == jnp.bfloat16


def test_flash_bf16_matches_f32_dense_reference():
    """The MXU dots run in the INPUT dtype (bf16 under AMP) with f32
    accumulation — outputs and grads must stay close to the f32 dense
    oracle within bf16 tolerance."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(3)
    qf = rng.randn(1, 2, 256, 64).astype(np.float32)
    kf = rng.randn(1, 2, 256, 64).astype(np.float32)
    vf = rng.randn(1, 2, 256, 64).astype(np.float32)
    scale = 1.0 / np.sqrt(64.0)

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)

    ref = dense(jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf))
    gref = jax.grad(lambda a, b, c: jnp.sum(dense(a, b, c) ** 2),
                    argnums=(0, 1, 2))(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf))

    qb = jnp.asarray(qf).astype(jnp.bfloat16)
    kb = jnp.asarray(kf).astype(jnp.bfloat16)
    vb = jnp.asarray(vf).astype(jnp.bfloat16)
    out = flash_attention(qb, kb, vb, scale, False, 128, 128)
    gb = jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, scale, False, 128, 128)
        .astype(jnp.float32) ** 2), argnums=(0, 1, 2))(qb, kb, vb)

    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)
    for g, gr in zip(gb, gref):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(gr), rtol=0.1, atol=0.25)
