"""GPT causal-decoder LM (models/gpt.py): trains, respects causality, and
ties the embedding/head weights."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.models import gpt


def _build(cfg):
    tokens, loss = gpt.build_lm_program(cfg)
    paddle.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, tokens, loss


def test_gpt_lm_trains_on_structured_sequences():
    cfg = gpt.GPTConfig.tiny()
    exe, tokens, loss = _build(cfg)
    rng = np.random.RandomState(0)
    # learnable structure: arithmetic mod-V sequences (next = prev + step)
    def batch(n=16):
        start = rng.randint(0, cfg.vocab_size, (n, 1))
        step = rng.randint(1, 5, (n, 1))
        seq = (start + step * np.arange(cfg.seq_len)) % cfg.vocab_size
        return seq.astype(np.int64)
    curve, = zip(*[exe.run(feed={"tokens": batch()}, fetch_list=[loss])
                   for _ in range(80)])
    curve = [float(np.asarray(v).reshape(-1)[0]) for v in curve]
    assert np.isfinite(curve).all()
    # measured: 6.25 -> ~2.6 by step 80 on this task
    assert curve[-1] < curve[0] * 0.6, curve[::10]


def test_gpt_is_causal():
    """Perturbing a future token must not change past positions' loss
    contributions — check via logits directly."""
    from paddle_tpu.fluid import layers as L
    cfg = gpt.GPTConfig.tiny()
    tokens = L.data(name="tokens", shape=[cfg.seq_len], dtype="int64")
    seq, wte = gpt.gpt_decoder(tokens, cfg)
    logits = L.matmul(seq, wte, transpose_y=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    t1 = rng.randint(0, cfg.vocab_size, (2, cfg.seq_len)).astype(np.int64)
    t2 = t1.copy()
    t2[:, -1] = (t2[:, -1] + 7) % cfg.vocab_size   # change ONLY the last
    a, = exe.run(feed={"tokens": t1}, fetch_list=[logits])
    b, = exe.run(feed={"tokens": t2}, fetch_list=[logits])
    np.testing.assert_allclose(a[:, :-1], b[:, :-1], rtol=1e-5, atol=1e-6)
    assert np.abs(a[:, -1] - b[:, -1]).max() > 1e-4  # last DOES differ


def test_gpt_embeddings_are_tied():
    """One [V, H] table serves lookup and head: training must move the
    SAME persistable (no separate lm_head param exists)."""
    cfg = gpt.GPTConfig.tiny()
    exe, tokens, loss = _build(cfg)
    names = [p.name for p in fluid.default_main_program().all_parameters()]
    assert "wte" in names and not any("head" in n for n in names)
    before = np.asarray(fluid.global_scope().find("wte")).copy()
    rng = np.random.RandomState(0)
    seq = rng.randint(0, cfg.vocab_size, (8, cfg.seq_len)).astype(np.int64)
    exe.run(feed={"tokens": seq}, fetch_list=[loss])
    after = np.asarray(fluid.global_scope().find("wte"))
    assert np.abs(after - before).max() > 0  # grads reached the tied table


def test_gpt_sequence_parallel_matches_dense():
    """The causal LM over an sp mesh (ring attention) must produce the same
    loss as the single-device build — the long-context training config."""
    import subprocess
    import sys
    import textwrap
    from conftest import cpu_mesh_env

    code = textwrap.dedent("""
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.fluid as fluid
        from paddle_tpu.models import gpt
        from paddle_tpu.parallel import build_mesh, DistConfig, attach
        from paddle_tpu.testing import reset_programs

        losses = {}
        for sp in (False, True):
            reset_programs(seed=0)
            cfg = gpt.GPTConfig.tiny()
            cfg.sequence_parallel = sp
            tokens, loss = gpt.build_lm_program(cfg)
            if sp:
                mesh = build_mesh(sp=4)
                attach(fluid.default_main_program(),
                       DistConfig(mesh=mesh,
                                  param_rules=gpt.tp_sharding_rules()))
            exe = fluid.Executor()
            exe.run(fluid.default_startup_program())
            rng = np.random.RandomState(0)
            seq = rng.randint(0, cfg.vocab_size,
                              (8, cfg.seq_len)).astype(np.int64)
            out, = exe.run(feed={"tokens": seq}, fetch_list=[loss])
            losses[sp] = float(np.asarray(out).reshape(-1)[0])
        delta = abs(losses[True] - losses[False])
        assert delta < 2e-4, losses
        print("OK", losses)
    """)
    r = subprocess.run([sys.executable, "-c", code], env=cpu_mesh_env(4),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "OK" in r.stdout
