"""Static control flow: While, while_loop, StaticRNN, TensorArray, Switch.

Mirrors reference tests test_while_op.py, test_while_loop_op.py,
test_static_rnn (recurrent_op), test_switch.py, test_array_read_write_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


@pytest.fixture(autouse=True)
def fresh_programs():
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)
    yield


def test_while_sum_of_squares():
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 10)
    acc = layers.fill_constant([1], "float32", 0.0)
    flag = layers.less_than(i, n)
    w = layers.While(flag)
    with w.block():
        fi = layers.cast(i, "float32")
        layers.assign(acc + fi * fi, acc)
        layers.increment(i)
        layers.less_than(i, n, cond=flag)
    exe = fluid.Executor()
    out, iv = exe.run(feed={}, fetch_list=[acc, i])
    assert out[0] == pytest.approx(sum(k * k for k in range(10)))
    assert iv[0] == 10


def test_while_loop_functional():
    def cond(i, s):
        return layers.less_than(i, layers.fill_constant([1], "int32", 5))

    def body(i, s):
        return [i + layers.fill_constant([1], "int32", 1), s * 2.0]

    i = layers.fill_constant([1], "int32", 0)
    s = layers.fill_constant([1], "float32", 1.0)
    i, s = layers.while_loop(cond, body, [i, s])
    exe = fluid.Executor()
    sv, iv = exe.run(feed={}, fetch_list=[s, i])
    assert sv[0] == pytest.approx(32.0)
    assert iv[0] == 5


def test_static_rnn_accumulate_matches_numpy():
    seq, batch, d = 6, 4, 3
    x_np = np.random.RandomState(0).randn(seq, batch, d).astype(np.float32)
    x = fluid.layers.data(name="x", shape=[batch, d], dtype="float32",
                          append_batch_size=False)
    x.shape = (seq, batch, d)
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(shape=[-1, d], batch_ref=x_t, init_value=0.0,
                            ref_batch_dim_idx=0, init_batch_dim_idx=0)
        h = layers.elementwise_add(h_prev, x_t)
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()
    exe = fluid.Executor()
    res, = exe.run(feed={"x": x_np}, fetch_list=[out])
    np.testing.assert_allclose(res, np.cumsum(x_np, axis=0), rtol=1e-5)


def test_static_rnn_is_differentiable():
    seq, batch, d = 5, 2, 4
    x_np = np.random.RandomState(1).randn(seq, batch, d).astype(np.float32)
    x = fluid.layers.data(name="x", shape=[batch, d], dtype="float32",
                          append_batch_size=False)
    x.shape = (seq, batch, d)
    w = layers.create_parameter([d, d], "float32", name="rnn_w")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(shape=[-1, d], batch_ref=x_t, init_value=0.0,
                            ref_batch_dim_idx=0, init_batch_dim_idx=0)
        h = layers.tanh(layers.elementwise_add(layers.matmul(x_t, w), h_prev))
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    loss = layers.reduce_mean(rnn())
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    l0, = exe.run(feed={"x": x_np}, fetch_list=[loss])
    for _ in range(5):
        l1, = exe.run(feed={"x": x_np}, fetch_list=[loss])
    assert np.isfinite(l1).all()
    assert l1 != l0  # parameters moved


def test_array_write_read_roundtrip():
    x = layers.fill_constant([2, 3], "float32", 7.0)
    i0 = layers.fill_constant([1], "int32", 0)
    i1 = layers.fill_constant([1], "int32", 1)
    arr = layers.array_write(x, i0)
    layers.array_write(x * 2.0, i1, array=arr)
    n = layers.array_length(arr)
    a0 = layers.array_read(arr, i0)
    a1 = layers.array_read(arr, i1)
    exe = fluid.Executor()
    nv, v0, v1 = exe.run(feed={}, fetch_list=[n, a0, a1])
    assert nv[0] == 2
    np.testing.assert_allclose(v0, np.full((2, 3), 7.0, np.float32))
    np.testing.assert_allclose(v1, np.full((2, 3), 14.0, np.float32))


def test_array_inside_while_collects_steps():
    n_steps = 4
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", n_steps)
    x = layers.fill_constant([3], "float32", 1.0)
    arr = layers.array_write(x, i)  # materialize buffer before the loop
    layers.increment(i)
    flag = layers.less_than(i, n)
    w = layers.While(flag)
    with w.block():
        fi = layers.cast(i, "float32")
        layers.array_write(layers.expand(fi, [3]), i, array=arr)
        layers.increment(i)
        layers.less_than(i, n, cond=flag)
    i2 = layers.fill_constant([1], "int32", 2)
    got = layers.array_read(arr, i2)
    length = layers.array_length(arr)
    exe = fluid.Executor()
    g, ln = exe.run(feed={}, fetch_list=[got, length])
    np.testing.assert_allclose(g, np.full(3, 2.0, np.float32))
    assert ln[0] == n_steps


def test_switch_piecewise():
    step = fluid.layers.data(name="step", shape=[1], dtype="float32",
                             append_batch_size=False)
    lr = layers.fill_constant([1], "float32", 0.0)
    b1 = layers.fill_constant([1], "float32", 100.0)
    b2 = layers.fill_constant([1], "float32", 200.0)
    with layers.Switch() as switch:
        with switch.case(layers.less_than(step, b1)):
            layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
        with switch.case(layers.less_than(step, b2)):
            layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
        with switch.default():
            layers.assign(layers.fill_constant([1], "float32", 0.001), lr)
    exe = fluid.Executor()
    for sval, want in [(50.0, 0.1), (150.0, 0.01), (500.0, 0.001)]:
        out, = exe.run(feed={"step": np.array([sval], np.float32)},
                       fetch_list=[lr])
        assert out[0] == pytest.approx(want)


def test_cond_basic_still_works():
    x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                          append_batch_size=False)
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))
    out = layers.cond(pred, lambda: x * 2.0, lambda: x - 1.0)
    exe = fluid.Executor()
    a, = exe.run(feed={"x": np.array([3.0], np.float32)}, fetch_list=[out])
    b, = exe.run(feed={"x": np.array([-3.0], np.float32)}, fetch_list=[out])
    assert a[0] == pytest.approx(6.0)
    assert b[0] == pytest.approx(-4.0)


def test_create_array_capacity_honored():
    x = layers.fill_constant([2], "float32", 3.0)
    arr = layers.create_array("float32", capacity=256)
    i = layers.fill_constant([1], "int32", 200)
    layers.array_write(x, i, array=arr)
    got = layers.array_read(arr, i)
    n = layers.array_length(arr)
    exe = fluid.Executor()
    g, nv = exe.run(feed={}, fetch_list=[got, n])
    np.testing.assert_allclose(g, np.full(2, 3.0, np.float32))
    assert nv[0] == 201


def test_array_first_write_inside_while_with_element_shape():
    arr = layers.create_array("float32", capacity=8, element_shape=[2])
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 4)
    flag = layers.less_than(i, n)
    w = layers.While(flag)
    with w.block():
        fi = layers.cast(i, "float32")
        layers.array_write(layers.expand(fi, [2]), i, array=arr)
        layers.increment(i)
        layers.less_than(i, n, cond=flag)
    got = layers.array_read(arr, layers.fill_constant([1], "int32", 3))
    exe = fluid.Executor()
    g, = exe.run(feed={}, fetch_list=[got])
    np.testing.assert_allclose(g, np.full(2, 3.0, np.float32))


def test_unmaterialized_array_in_while_raises_clearly():
    arr = layers.create_array("float32")
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 4)
    flag = layers.less_than(i, n)
    w = layers.While(flag)
    with w.block():
        fi = layers.cast(i, "float32")
        layers.array_write(layers.expand(fi, [2]), i, array=arr)
        layers.increment(i)
        layers.less_than(i, n, cond=flag)
    exe = fluid.Executor()
    with pytest.raises(Exception, match="element_shape|materialized"):
        exe.run(feed={}, fetch_list=[layers.array_length(arr)])


def test_array_write_grows_for_static_index():
    """A build-time-known index past capacity grows the buffer (reference
    LoDTensorArray grows dynamically) instead of silently dropping."""
    x = layers.fill_constant([3], "float32", 7.0)
    arr = layers.create_array("float32", capacity=2)
    i0 = layers.fill_constant([1], "int64", 0)
    i5 = layers.fill_constant([1], "int64", 5)
    layers.array_write(x, i0, array=arr)
    layers.array_write(x * 2.0, i5, array=arr)   # beyond capacity=2
    got = layers.array_read(arr, i5)
    n = layers.array_length(arr)
    exe = fluid.Executor()
    out, ln = exe.run(feed={}, fetch_list=[got, n])
    np.testing.assert_allclose(out, [14.0, 14.0, 14.0])
    assert int(np.asarray(ln).reshape(-1)[0]) == 6
