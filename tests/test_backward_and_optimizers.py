"""append_backward correctness: analytic grads vs numeric finite differences.

Modeled on the reference OpTest check_grad machinery
(unittests/op_test.py:1279, get_numeric_gradient :58).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def _numeric_grad(run_loss, x0, eps=1e-3):
    g = np.zeros_like(x0)
    flat = x0.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        lp = run_loss(x0)
        flat[i] = orig - eps
        lm = run_loss(x0)
        flat[i] = orig
        gf[i] = (lp - lm) / (2 * eps)
    return g


def test_fc_grad_matches_numeric():
    np.random.seed(0)
    x = fluid.layers.data(name="x", shape=[3], dtype="float32")
    w0 = np.random.rand(3, 2).astype(np.float32)
    w = fluid.layers.create_parameter(
        [3, 2], "float32", name="W",
        default_initializer=paddle.initializer.NumpyArrayInitializer(w0))
    out = fluid.layers.mul(x, w)
    loss = fluid.layers.mean(fluid.layers.square(out))
    pgs = paddle.append_backward(loss)
    assert len(pgs) == 1
    grad_var = pgs[0][1]

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(4, 3).astype(np.float32)
    analytic, = exe.run(feed={"x": xv}, fetch_list=[grad_var])

    def run_loss(wv):
        out = xv @ wv
        return np.mean(out ** 2)

    numeric = _numeric_grad(run_loss, w0.copy())
    np.testing.assert_allclose(analytic, numeric, rtol=1e-2, atol=1e-3)


def test_grad_accumulation_multi_consumer():
    # param used by two branches -> grads must sum
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    w0 = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    w = fluid.layers.create_parameter(
        [2, 2], "float32", name="W2",
        default_initializer=paddle.initializer.NumpyArrayInitializer(w0))
    a = fluid.layers.mul(x, w)
    b = fluid.layers.mul(fluid.layers.square(x), w)
    loss = fluid.layers.mean(fluid.layers.elementwise_add(a, b))
    pgs = paddle.append_backward(loss)
    grad_var = pgs[0][1]
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(3, 2).astype(np.float32)
    analytic, = exe.run(feed={"x": xv}, fetch_list=[grad_var])

    def run_loss(wv):
        return np.mean(xv @ wv + (xv ** 2) @ wv)

    numeric = _numeric_grad(run_loss, w0.copy())
    np.testing.assert_allclose(analytic, numeric, rtol=1e-2, atol=1e-3)


def test_sgd_descends_quadratic():
    w0 = np.array([5.0, -3.0], np.float32)
    w = fluid.layers.create_parameter(
        [2], "float32", name="Wq",
        default_initializer=paddle.initializer.NumpyArrayInitializer(w0))
    loss = fluid.layers.mean(fluid.layers.square(w))
    opt = paddle.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = [float(exe.run(fetch_list=[loss])[0]) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.1
    # analytic: w_{t+1} = w_t (1 - 2*lr/n)... just check monotone decrease
    assert all(b <= a + 1e-6 for a, b in zip(losses, losses[1:]))


@pytest.mark.parametrize("opt_name", ["SGD", "Momentum", "Adam", "Adagrad",
                                      "RMSProp", "Lamb", "Adamax", "AdamW",
                                      "LarsMomentum"])
def test_all_optimizers_reduce_loss(opt_name):
    np.random.seed(1)
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    kw = {}
    if opt_name == "RMSProp":
        kw = {"learning_rate": 0.01}
    else:
        kw = {"learning_rate": 0.05}
    opt = getattr(paddle.optimizer, opt_name)(**kw)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(16, 4).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.5).astype(np.float32)
    first = None
    last = None
    for i in range(30):
        lv, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first, f"{opt_name}: {first} -> {last}"


def test_gradient_clip_by_global_norm():
    w0 = np.full((4,), 100.0, np.float32)
    w = fluid.layers.create_parameter(
        [4], "float32", name="Wc",
        default_initializer=paddle.initializer.NumpyArrayInitializer(w0))
    loss = fluid.layers.mean(fluid.layers.square(w))
    opt = paddle.optimizer.SGD(
        learning_rate=1.0, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    w_after = paddle.global_scope().numpy("Wc")
    # grad = 2w/4 = 50 each, global norm 100 -> scaled to 1 -> step of ~0.5
    np.testing.assert_allclose(w_after, w0 - 0.5, atol=1e-4)


def test_regularizer_l2():
    w0 = np.array([2.0], np.float32)
    w = fluid.layers.create_parameter(
        [1], "float32", name="Wr",
        default_initializer=paddle.initializer.NumpyArrayInitializer(w0))
    loss = fluid.layers.mean(w)  # d/dw = 1
    opt = paddle.optimizer.SGD(
        learning_rate=1.0,
        regularization=paddle.regularizer.L2Decay(0.5))
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    # grad = 1 + 0.5*2 = 2 -> w = 2 - 2 = 0
    np.testing.assert_allclose(paddle.global_scope().numpy("Wr"), [0.0],
                               atol=1e-5)
