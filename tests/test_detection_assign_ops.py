"""Round-4 detection target-assignment ops vs independent numpy loop
oracles (reference per-op unittests pattern: test_rpn_target_assign_op.py,
test_generate_proposal_labels_op.py, test_locality_aware_nms_op.py,
test_roi_perspective_transform_op.py)."""
import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (registers ops)
from op_test import run_op, check_grad

R = np.random.RandomState(7)


def _iou1(a, b):
    """Pixel-convention IoU (+1 widths)."""
    iw = min(a[2], b[2]) - max(a[0], b[0]) + 1.0
    ih = min(a[3], b[3]) - max(a[1], b[1]) + 1.0
    if iw <= 0 or ih <= 0:
        return 0.0
    inter = iw * ih
    aa = (a[2] - a[0] + 1.0) * (a[3] - a[1] + 1.0)
    ab = (b[2] - b[0] + 1.0) * (b[3] - b[1] + 1.0)
    return inter / (aa + ab - inter)


def _delta1(ex, gt, w=None):
    ew = ex[2] - ex[0] + 1.0
    eh = ex[3] - ex[1] + 1.0
    ecx, ecy = ex[0] + 0.5 * ew, ex[1] + 0.5 * eh
    gw = gt[2] - gt[0] + 1.0
    gh = gt[3] - gt[1] + 1.0
    gcx, gcy = gt[0] + 0.5 * gw, gt[1] + 0.5 * gh
    d = np.array([(gcx - ecx) / ew, (gcy - ecy) / eh,
                  np.log(gw / ew), np.log(gh / eh)])
    return d / np.asarray(w) if w is not None else d


def _grid_anchors(n=4, size=6.0, stride=8.0):
    out = []
    for i in range(n):
        for j in range(n):
            cx, cy = j * stride + 4, i * stride + 4
            out.append([cx - size / 2, cy - size / 2,
                        cx + size / 2, cy + size / 2])
            out.append([cx - size, cy - size / 4,
                        cx + size, cy + size / 4])
    return np.asarray(out, np.float32)


def test_rpn_target_assign_matches_loop_oracle():
    anchors = _grid_anchors()                       # [32, 4]
    a = anchors.shape[0]
    gt = np.zeros((1, 3, 4), np.float32)
    gt[0, 0] = [2, 2, 12, 12]
    gt[0, 1] = [14, 14, 30, 28]                     # second real gt
    # gt[0, 2] stays zero = padding
    crowd = np.zeros((1, 3), np.int64)
    im_info = np.asarray([[32.0, 32.0, 1.0]], np.float32)
    attrs = {"rpn_batch_size_per_im": 12, "rpn_straddle_thresh": 0.0,
             "rpn_fg_fraction": 0.5, "rpn_positive_overlap": 0.6,
             "rpn_negative_overlap": 0.3, "use_random": False}
    out = run_op("rpn_target_assign",
                 {"Anchor": [anchors], "GtBoxes": [gt], "IsCrowd": [crowd],
                  "ImInfo": [im_info]}, attrs)
    lab = np.asarray(out["TargetLabel"][0])[0, :, 0]
    sw = np.asarray(out["ScoreWeight"][0])[0, :, 0]
    tb = np.asarray(out["TargetBBox"][0])[0]
    bw = np.asarray(out["BBoxInsideWeight"][0])[0]

    # oracle
    inside = [(anchors[i, 0] >= 0 and anchors[i, 1] >= 0
               and anchors[i, 2] < 32 and anchors[i, 3] < 32)
              for i in range(a)]
    iou = np.array([[_iou1(anchors[i], gt[0, g]) if g < 2 else -1.0
                     for g in range(3)] for i in range(a)])
    amax = iou.max(1)
    aarg = iou.argmax(1)
    gmax = np.where(np.asarray(inside)[:, None], iou, -1.0).max(0)
    fg = [inside[i] and (amax[i] >= 0.6 or any(
        iou[i, g] >= gmax[g] - 1e-5 and gmax[g] > 0 for g in range(2)))
        for i in range(a)]
    bg = [inside[i] and amax[i] < 0.3 and not fg[i] for i in range(a)]
    fg_idx = [i for i in range(a) if fg[i]][:6]     # use_random=False: first-N
    n_bg = 12 - len(fg_idx)
    bg_idx = [i for i in range(a) if bg[i]][:n_bg]
    exp_lab = np.zeros(a)
    exp_lab[fg_idx] = 1.0
    exp_sw = np.zeros(a)
    exp_sw[fg_idx + bg_idx] = 1.0
    np.testing.assert_allclose(lab, exp_lab)
    np.testing.assert_allclose(sw, exp_sw)
    for i in fg_idx:
        np.testing.assert_allclose(
            tb[i], _delta1(anchors[i], gt[0, aarg[i]]), rtol=1e-5,
            atol=1e-5)
        np.testing.assert_allclose(bw[i], 1.0)
    assert np.all(tb[~np.asarray(fg, bool)] == 0.0)
    assert np.all(bw.sum(1)[~np.asarray(fg, bool)] == 0.0)


def test_retinanet_target_assign_labels_and_ignore_band():
    anchors = _grid_anchors()
    a = anchors.shape[0]
    gt = np.zeros((1, 2, 4), np.float32)
    gt[0, 0] = [2, 2, 12, 12]
    labels = np.asarray([[3, 0]], np.int64)
    im_info = np.asarray([[32.0, 32.0, 1.0]], np.float32)
    out = run_op("retinanet_target_assign",
                 {"Anchor": [anchors], "GtBoxes": [gt],
                  "GtLabels": [labels], "ImInfo": [im_info]},
                 {"positive_overlap": 0.5, "negative_overlap": 0.4})
    lab = np.asarray(out["TargetLabel"][0])[0, :, 0]
    sw = np.asarray(out["ScoreWeight"][0])[0, :, 0]
    fgn = int(np.asarray(out["ForegroundNumber"][0])[0, 0])
    iou = np.array([_iou1(anchors[i], gt[0, 0]) for i in range(a)])
    best = iou.argmax()
    fg = (iou >= 0.5) | (np.arange(a) == best)
    ignore = ~fg & (iou >= 0.4)
    assert fgn == fg.sum()
    np.testing.assert_array_equal(lab[fg], 3)
    np.testing.assert_array_equal(sw[ignore], 0.0)
    np.testing.assert_array_equal(lab[~fg], 0)
    np.testing.assert_array_equal(sw[fg], 1.0)


def test_generate_proposal_labels_matches_loop_oracle():
    r, g, bs = 8, 2, 6
    rois = np.zeros((8, 4), np.float32)
    rois[0] = [2, 2, 11, 11]        # IoU with gt0 high -> fg
    rois[1] = [3, 3, 13, 13]        # fg
    rois[2] = [20, 20, 30, 30]      # bg (no overlap)
    rois[3] = [0, 16, 10, 30]       # bg
    rois[4] = [4, 4, 30, 30]        # mid overlap -> depends
    rois[5] = [16, 0, 30, 12]       # bg
    # rows 6..7 are dead padding (count=6)
    nums = np.asarray([6], np.int32)
    gt = np.zeros((1, g, 4), np.float32)
    gt[0, 0] = [2, 2, 12, 12]
    gt_cls = np.asarray([[2, 0]], np.int64)
    crowd = np.zeros((1, g), np.int64)
    im_info = np.asarray([[32.0, 32.0, 1.0]], np.float32)
    attrs = {"batch_size_per_im": bs, "fg_fraction": 0.5, "fg_thresh": 0.5,
             "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2], "class_nums": 3,
             "use_random": False}
    out = run_op("generate_proposal_labels",
                 {"RpnRois": [rois], "GtClasses": [gt_cls],
                  "IsCrowd": [crowd], "GtBoxes": [gt], "ImInfo": [im_info],
                  "RpnRoisNum": [nums]}, attrs)
    # oracle: candidates = 6 live rois + 1 valid gt
    cand = np.concatenate([rois, gt[0]], 0)
    live = [True] * 6 + [False] * 2 + [True, False]
    mov = np.array([_iou1(cand[i], gt[0, 0]) for i in range(r + g)])
    fg = [live[i] and mov[i] >= 0.5 for i in range(r + g)]
    bg = [live[i] and 0.0 <= mov[i] < 0.5 for i in range(r + g)]
    fg_idx = [i for i in range(r + g) if fg[i]][:3]
    bg_idx = [i for i in range(r + g) if bg[i]][:bs - len(fg_idx)]
    got_rois = np.asarray(out["Rois"][0])
    got_lab = np.asarray(out["LabelsInt32"][0])[:, 0]
    got_tgt = np.asarray(out["BboxTargets"][0])
    got_w = np.asarray(out["BboxInsideWeights"][0])
    got_cnt = int(np.asarray(out["RoisNum"][0])[0])
    got_rw = np.asarray(out["RoiWeights"][0])[:, 0]
    assert got_cnt == len(fg_idx) + len(bg_idx)
    np.testing.assert_allclose(got_rw,
                               (np.arange(bs) < got_cnt).astype(np.float32))
    for row, i in enumerate(fg_idx):
        np.testing.assert_allclose(got_rois[row], cand[i])
        assert got_lab[row] == 2
        np.testing.assert_allclose(
            got_tgt[row, 8:12],
            _delta1(cand[i], gt[0, 0], [0.1, 0.1, 0.2, 0.2]),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(got_w[row, 8:12], 1.0)
        assert np.all(got_tgt[row, :8] == 0)
    for row, i in enumerate(bg_idx):
        np.testing.assert_allclose(got_rois[len(fg_idx) + row], cand[i])
        assert got_lab[len(fg_idx) + row] == 0
        assert np.all(got_w[len(fg_idx) + row] == 0)


def test_generate_mask_labels_exact_rectangle():
    hm = wm = 32
    res = 8
    segm = np.zeros((1, 2, hm, wm), np.float32)
    segm[0, 0, 8:24, 8:24] = 1.0          # gt 0 bitmap: square
    gt = np.zeros((1, 2, 4), np.float32)
    gt[0, 0] = [8, 8, 23, 23]
    gt_cls = np.asarray([[1, 0]], np.int64)
    rois = np.zeros((4, 4), np.float32)
    rois[0] = [8, 8, 24, 24]              # fg roi exactly on the square
    rois[1] = [0, 0, 31, 31]              # fg roi covering whole image
    rois[2] = [25, 25, 31, 31]            # bg roi
    labels = np.asarray([[1], [1], [0], [0]], np.int32)
    nums = np.asarray([3], np.int32)
    im_info = np.asarray([[32.0, 32.0, 1.0]], np.float32)
    out = run_op("generate_mask_labels",
                 {"ImInfo": [im_info], "GtClasses": [gt_cls],
                  "GtSegms": [segm], "Rois": [rois],
                  "LabelsInt32": [labels], "GtBoxes": [gt],
                  "RoisNum": [nums]},
                 {"num_classes": 2, "resolution": res})
    mask = np.asarray(out["MaskInt32"][0]).reshape(4, 2, res, res)
    has = np.asarray(out["RoiHasMaskInt32"][0])[:, 0]
    np.testing.assert_array_equal(has, [1, 1, 0, 0])
    # roi 0 covers exactly the square: class-1 slot all ones
    np.testing.assert_array_equal(mask[0, 1], 1)
    np.testing.assert_array_equal(mask[0, 0], -1)   # other class ignored
    # roi 1 covers the whole image: interior ~quarter ones
    m1 = mask[1, 1]
    assert m1.min() == 0 and m1.max() == 1
    frac = (m1 == 1).mean()
    assert 0.1 < frac < 0.45
    # bg / padding rows fully ignored
    np.testing.assert_array_equal(mask[2], -1)
    np.testing.assert_array_equal(mask[3], -1)


def _jac(a, b):
    iw = min(a[2], b[2]) - max(a[0], b[0])
    ih = min(a[3], b[3]) - max(a[1], b[1])
    if iw <= 0 or ih <= 0:
        return 0.0
    inter = iw * ih
    s = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1])
    return inter / (s - inter)


def test_locality_aware_nms_rect_matches_loop_oracle():
    boxes = np.asarray([
        [0.0, 0.0, 0.40, 0.40],
        [0.02, 0.02, 0.42, 0.42],     # merges with 0
        [0.03, 0.01, 0.41, 0.43],     # merges again
        [0.60, 0.60, 0.90, 0.90],     # new cluster
        [0.61, 0.61, 0.91, 0.91],     # merges with 3
        [0.10, 0.70, 0.30, 0.95],     # isolated
    ], np.float32)[None]
    scores = np.asarray([0.8, 0.7, 0.6, 0.9, 0.5, 0.3],
                        np.float32)[None, None]
    attrs = {"score_threshold": 0.05, "nms_top_k": 10, "keep_top_k": 5,
             "nms_threshold": 0.3, "normalized": True,
             "background_label": -1}
    out = run_op("locality_aware_nms",
                 {"BBoxes": [boxes], "Scores": [scores]}, attrs)
    got = np.asarray(out["Out"][0])
    cnt = int(np.asarray(out["OutCount"][0])[0])

    # oracle merge pass (reference GetMaxScoreIndexWithLocalityAware)
    bx = boxes[0].copy()
    sc = scores[0, 0].copy()
    skip = [False] * 6
    index = -1
    for i in range(6):
        if index > -1:
            if _jac(bx[i], bx[index]) > 0.3:
                bx[index] = (bx[i] * sc[i] + bx[index] * sc[index]) \
                    / (sc[i] + sc[index])
                sc[index] += sc[i]
                skip[i] = True
            else:
                index = i
        else:
            index = i
    merged = [(sc[i], bx[i]) for i in range(6) if not skip[i]
              and sc[i] > 0.05]
    merged.sort(key=lambda t: -t[0])
    kept = []
    for s, b in merged:
        if all(_jac(b, kb) <= 0.3 for _, kb in kept):
            kept.append((s, b))
    assert cnt == len(kept)
    for row, (s, b) in enumerate(kept):
        assert got[row, 0] == 0           # class label
        np.testing.assert_allclose(got[row, 1], s, rtol=1e-5)
        np.testing.assert_allclose(got[row, 2:], b, rtol=1e-5, atol=1e-6)


def test_quad_iou_known_values():
    from paddle_tpu.ops.detection_assign_ops import _quad_iou
    import jax.numpy as jnp
    sq = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0]])
    shifted = sq + jnp.asarray([0.5, 0.0])
    # overlap 0.5, union 1.5
    np.testing.assert_allclose(float(_quad_iou(sq, shifted)), 1.0 / 3.0,
                               rtol=1e-5)
    np.testing.assert_allclose(float(_quad_iou(sq, sq)), 1.0, rtol=1e-5)
    far = sq + jnp.asarray([5.0, 5.0])
    np.testing.assert_allclose(float(_quad_iou(sq, far)), 0.0, atol=1e-7)
    # clockwise winding must not break the clipper
    cw = sq[::-1]
    np.testing.assert_allclose(float(_quad_iou(cw, shifted)), 1.0 / 3.0,
                               rtol=1e-5)
    # 45-degree diamond inside the square: inter = diamond area 0.5
    diamond = jnp.asarray([[0.5, 0.0], [1.0, 0.5], [0.5, 1.0], [0.0, 0.5]])
    np.testing.assert_allclose(float(_quad_iou(sq, diamond)), 0.5 / 1.0,
                               rtol=1e-5)


def test_locality_aware_nms_quads():
    """Two overlapping quads merge; the far one survives separately."""
    q = np.asarray([
        [0, 0, 10, 0, 10, 10, 0, 10],
        [1, 1, 11, 1, 11, 11, 1, 11],
        [30, 30, 40, 30, 40, 40, 30, 40],
    ], np.float32)[None]
    s = np.asarray([0.6, 0.4, 0.9], np.float32)[None, None]
    out = run_op("locality_aware_nms", {"BBoxes": [q], "Scores": [s]},
                 {"score_threshold": 0.05, "nms_top_k": 5, "keep_top_k": 3,
                  "nms_threshold": 0.3, "normalized": False,
                  "background_label": -1})
    got = np.asarray(out["Out"][0])
    cnt = int(np.asarray(out["OutCount"][0])[0])
    assert cnt == 2
    # merged quad = weighted mean, score = sum
    exp = (q[0, 0] * 0.6 + q[0, 1] * 0.4) / 1.0
    np.testing.assert_allclose(got[0, 1], 1.0, rtol=1e-5)      # 0.6 + 0.4
    np.testing.assert_allclose(got[1, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(got[0, 2:], exp, rtol=1e-5)


def test_roi_perspective_transform_axis_aligned_is_crop():
    n, c, h, w = 1, 2, 12, 16
    x = R.randn(n, c, h, w).astype(np.float32)
    th, tw = 6, 8
    # quad = axis-aligned rect (2,1)-(9,6): warp == integer crop
    rois = np.asarray([[2, 1, 9, 1, 9, 6, 2, 6]], np.float32)
    out = run_op("roi_perspective_transform",
                 {"X": [x], "ROIs": [rois]},
                 {"spatial_scale": 1.0, "transformed_height": th,
                  "transformed_width": tw})
    got = np.asarray(out["Out"][0])
    mask = np.asarray(out["Mask"][0])
    np.testing.assert_allclose(got[0], x[0, :, 1:7, 2:10], rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_array_equal(mask, 1)
    hm = np.asarray(out["TransformMatrix"][0]).reshape(3, 3)
    # H maps (0,0)->(2,1) and (tw-1,0)->(9,1)
    p = hm @ np.asarray([0.0, 0.0, 1.0])
    np.testing.assert_allclose(p[:2] / p[2], [2, 1], atol=1e-4)
    p = hm @ np.asarray([tw - 1.0, 0.0, 1.0])
    np.testing.assert_allclose(p[:2] / p[2], [9, 1], atol=1e-4)


def test_roi_perspective_transform_grad_flows():
    x = R.randn(1, 1, 8, 8).astype(np.float32)
    rois = np.asarray([[1, 1, 6, 2, 6, 6, 1, 5]], np.float32)  # real quad
    check_grad("roi_perspective_transform", {"X": [x], "ROIs": [rois]},
               {"spatial_scale": 1.0, "transformed_height": 4,
                "transformed_width": 4}, wrt=["X"], out_slots=("Out",))


def test_ssd_loss_matches_loop_oracle():
    p, g, ncls = 6, 2, 3
    prior = np.asarray([
        [0.0, 0.0, 0.3, 0.3],
        [0.1, 0.1, 0.4, 0.4],
        [0.5, 0.5, 0.9, 0.9],
        [0.6, 0.6, 1.0, 1.0],
        [0.0, 0.6, 0.3, 1.0],
        [0.7, 0.0, 1.0, 0.3],
    ], np.float32)
    gt = np.zeros((1, g, 4), np.float32)
    gt[0, 0] = [0.05, 0.05, 0.35, 0.35]
    gt[0, 1] = [0.55, 0.55, 0.95, 0.95]
    gl = np.asarray([[1, 2]], np.int64)[..., None]
    loc = R.randn(1, p, 4).astype(np.float32) * 0.1
    conf = R.randn(1, p, ncls).astype(np.float32)
    out = run_op("ssd_loss",
                 {"Location": [loc], "Confidence": [conf], "GtBox": [gt],
                  "GtLabel": [gl], "PriorBox": [prior]},
                 {"overlap_threshold": 0.5, "neg_pos_ratio": 1.0,
                  "neg_overlap": 0.5, "background_label": 0})
    got = float(np.asarray(out["Loss"][0])[0, 0])

    # --- oracle ---
    iou = np.array([[_jac(gt[0, gi], prior[pi]) for pi in range(p)]
                    for gi in range(g)])
    # greedy bipartite
    d = iou.copy()
    match = np.full(p, -1)
    mdist = np.zeros(p)
    for _ in range(min(g, p)):
        gi, pi = np.unravel_index(d.argmax(), d.shape)
        if d[gi, pi] <= 0:
            break
        match[pi] = gi
        mdist[pi] = d[gi, pi]
        d[gi, :] = -1
        d[:, pi] = -1
    # per_prediction extras
    for pi in range(p):
        if match[pi] < 0 and iou[:, pi].max() >= 0.5:
            match[pi] = iou[:, pi].argmax()
            mdist[pi] = iou[:, pi].max()
    tgt = np.where(match >= 0, gl[0, np.maximum(match, 0), 0], 0)
    lp = conf[0] - conf[0].max(1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(1, keepdims=True))
    ce = -lp[np.arange(p), tgt]
    is_neg = (match < 0) & (mdist < 0.5)
    n_pos = (match >= 0).sum()
    n_neg = min(int(n_pos * 1.0), is_neg.sum())
    neg_order = np.argsort(-np.where(is_neg, ce, -np.inf))[:n_neg]
    var = np.asarray([0.1, 0.1, 0.2, 0.2])
    loss = 0.0
    for pi in range(p):
        if match[pi] >= 0:
            pr = prior[pi]
            gb = gt[0, match[pi]]
            pw, ph = pr[2] - pr[0], pr[3] - pr[1]
            gw, gh = gb[2] - gb[0], gb[3] - gb[1]
            t = np.array([((gb[0] + gb[2]) / 2 - (pr[0] + pr[2]) / 2) / pw,
                          ((gb[1] + gb[3]) / 2 - (pr[1] + pr[3]) / 2) / ph,
                          np.log(gw / pw), np.log(gh / ph)]) / var
            diff = np.abs(loc[0, pi] - t)
            loss += np.sum(np.where(diff < 1, 0.5 * diff ** 2, diff - 0.5))
            loss += ce[pi]
    loss += ce[neg_order].sum()
    loss /= max(n_pos, 1)
    np.testing.assert_allclose(got, loss, rtol=1e-4)
