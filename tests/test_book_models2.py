"""Book tests #2: recommender system, understand-sentiment, and label
semantic roles (reference book/test_recommender_system.py,
notest_understand_sentiment.py, test_label_semantic_roles.py) — with these,
every reference book chapter has a training test."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

# Tier-1 rebalance (ISSUE 16): heaviest suite in the sweep (~170s) and the
# layer/training surface it covers is already exercised by test_book_models
# + the op-level suites; ci.py shards still run it on every CI pass.
pytestmark = pytest.mark.slow


def test_recommender_system_dual_tower():
    """User tower (id/gender/age/job embeddings -> fc) and movie tower
    (id/category) scored by cos_sim, trained with square error on synthetic
    preferences that depend on a hidden (user_bucket, movie_bucket)
    affinity — learnable structure, reference model shape."""
    USERS, MOVIES, CATS = 30, 40, 4

    uid = layers.data(name="uid", shape=[1], dtype="int64")
    gender = layers.data(name="gender", shape=[1], dtype="int64")
    age = layers.data(name="age", shape=[1], dtype="int64")
    job = layers.data(name="job", shape=[1], dtype="int64")
    mid = layers.data(name="mid", shape=[1], dtype="int64")
    cat = layers.data(name="cat", shape=[1], dtype="int64")
    score = layers.data(name="score", shape=[1], dtype="float32")

    def emb(x, size, dim=8):
        e = layers.embedding(x, size=[size, dim])
        return layers.reshape(e, [-1, dim])

    usr = layers.concat([emb(uid, USERS), emb(gender, 2), emb(age, 7),
                         emb(job, 10)], axis=1)
    usr = layers.fc(usr, 16, act="tanh")
    mov = layers.concat([emb(mid, MOVIES), emb(cat, CATS)], axis=1)
    mov = layers.fc(mov, 16, act="tanh")
    sim = layers.cos_sim(usr, mov)
    pred = layers.scale(sim, scale=5.0)   # book scales cosine to 0..5
    loss = layers.mean(layers.square_error_cost(pred, score))
    paddle.optimizer.Adam(learning_rate=5e-3).minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    affinity = rng.rand(3, 2) * 4 + 0.5     # hidden bucket affinity

    def batch(n=64):
        u = rng.randint(0, USERS, (n, 1))
        m = rng.randint(0, MOVIES, (n, 1))
        f = {"uid": u.astype(np.int64),
             "gender": (u % 2).astype(np.int64),
             "age": (u % 7).astype(np.int64),
             "job": (u % 10).astype(np.int64),
             "mid": m.astype(np.int64),
             "cat": (m % CATS).astype(np.int64)}
        s = affinity[u[:, 0] % 3, m[:, 0] % 2]
        f["score"] = (s[:, None] + 0.1 * rng.randn(n, 1)).astype(np.float32)
        return f

    curve = []
    for _ in range(120):
        out, = exe.run(feed=batch(), fetch_list=[loss])
        curve.append(float(np.asarray(out).reshape(-1)[0]))
    assert np.isfinite(curve).all()
    assert curve[-1] < curve[0] * 0.45, (curve[0], curve[-1])


def test_understand_sentiment_lstm():
    """Stacked embedding -> gate-projected LSTM -> last-state pooling ->
    softmax classifier on synthetic separable 'sentiment': positive
    sequences draw from the top half of the vocab."""
    V, T, H = 64, 12, 32
    words = layers.data(name="words", shape=[T], dtype="int64")
    lens = layers.data(name="lens", shape=[], dtype="int64")
    label = layers.data(name="label", shape=[1], dtype="int64")

    e = layers.embedding(layers.unsqueeze(words, [2]), size=[V, H])
    e = layers.reshape(e, [-1, T, H])
    proj = layers.fc(e, 4 * H, num_flatten_dims=2)
    hidden, _ = layers.dynamic_lstm(proj, 4 * H, length=lens)
    feat = layers.sequence_pool(hidden, "last", length=lens)
    logits = layers.fc(feat, 2)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    paddle.optimizer.Adam(learning_rate=5e-3).minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)

    def batch(n=32):
        y = rng.randint(0, 2, (n, 1))
        lo = np.where(y[:, 0] == 1, V // 2, 0)
        w = (lo[:, None] + rng.randint(0, V // 2, (n, T)))
        ln = rng.randint(T // 2, T + 1, (n,))
        return {"words": w.astype(np.int64), "lens": ln.astype(np.int64),
                "label": y.astype(np.int64)}

    accs = []
    for _ in range(60):
        feed = batch()
        _, a = exe.run(feed=feed, fetch_list=[loss, acc])
        accs.append(float(np.asarray(a).reshape(-1)[0]))
    assert np.mean(accs[-10:]) > 0.9, accs[::10]


def test_label_semantic_roles_srl():
    """Book chapter test_label_semantic_roles.py: multi-feature embeddings
    (word, predicate, context mark) -> stacked forward+backward LSTM ->
    CRF over role labels; Viterbi decode beats chance after training."""
    from paddle_tpu.layer_helper import ParamAttr
    B, T, V, ROLES, H = 8, 8, 40, 5, 24

    word = layers.data(name="word", shape=[T], dtype="int64")
    pred = layers.data(name="pred", shape=[T], dtype="int64")
    mark = layers.data(name="mark", shape=[T], dtype="int64")
    roles = layers.data(name="roles", shape=[T], dtype="int64")
    lens = layers.data(name="lens", shape=[1], dtype="int32")

    def emb(x, size, dim=16):
        e = layers.embedding(layers.unsqueeze(x, [2]), [size, dim])
        return layers.reshape(e, [0, 0, dim])

    feat = layers.concat([emb(word, V), emb(pred, V), emb(mark, 2, 4)],
                         axis=2)
    # stacked bi-directional pass (the book stacks depth alternating
    # directions; one fwd + one bwd layer keeps the shape, CPU-test sized)
    fwd_in = layers.fc(feat, 4 * H, num_flatten_dims=2)
    fwd, _ = layers.dynamic_lstm(fwd_in, 4 * H, length=layers.reshape(
        lens, [-1]))
    bwd_in = layers.fc(feat, 4 * H, num_flatten_dims=2)
    bwd, _ = layers.dynamic_lstm(bwd_in, 4 * H, is_reverse=True,
                                 length=layers.reshape(lens, [-1]))
    hidden = layers.concat([fwd, bwd], axis=2)
    emission = layers.fc(hidden, ROLES, num_flatten_dims=2)
    nll = layers.linear_chain_crf(
        emission, roles, param_attr=ParamAttr(name="srl_crf_trans"),
        length=lens)
    loss = layers.mean(nll)
    test_prog = fluid.default_main_program().clone(for_test=True)
    paddle.optimizer.Adam(learning_rate=0.03).minimize(loss)
    with fluid.program_guard(test_prog):
        path = layers.crf_decoding(
            test_prog.global_block().var(emission.name),
            param_attr=ParamAttr(name="srl_crf_trans"),
            length=test_prog.global_block().var(lens.name))

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    wv = rng.randint(0, V, (B, T)).astype(np.int64)
    pv = np.tile(wv[:, :1], (1, T))            # predicate broadcast
    mv = (np.arange(T)[None, :] == 0).astype(np.int64) * np.ones(
        (B, 1), np.int64)
    # role rule: depends on word parity and predicate parity — learnable
    rv = ((wv % 2) * 2 + (pv % 2)).astype(np.int64) % ROLES
    lv = rng.randint(4, T + 1, (B, 1)).astype(np.int32)
    feed = {"word": wv, "pred": pv, "mark": mv, "roles": rv, "lens": lv}

    losses = [float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                    .reshape(-1)[0]) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.35, (losses[0], losses[-1])
    got, = exe.run(test_prog, feed=feed, fetch_list=[path])
    live = np.arange(T)[None, :] < lv
    acc = (np.asarray(got) == rv)[live].mean()
    assert acc > 0.8, f"SRL viterbi accuracy {acc:.2f}"


def test_se_resnext_trains_and_groups_convs():
    """SE-ResNeXt-50 (reference dist_se_resnext.py:51, its canonical dist
    test model): tiny-image variant must train — loss decreases over a few
    SGD steps — and the trunk must contain grouped (cardinality) convs."""
    from paddle_tpu.models.se_resnext import build_se_resnext_program

    img, label, loss, acc = build_se_resnext_program(
        class_dim=4, depth=50, image_shape=(3, 32, 32))
    prog = fluid.default_main_program()
    grouped = [op for op in prog.global_block().ops
               if op.type == "conv2d" and op.attrs.get("groups", 1) > 1]
    assert len(grouped) == 16, f"expected 16 cardinality convs, {len(grouped)}"

    paddle.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    # learnable signal: class = quadrant with the bright patch
    xs = rng.rand(32, 3, 32, 32).astype(np.float32) * 0.1
    ys = rng.randint(0, 4, (32, 1)).astype(np.int64)
    for i in range(32):
        qy, qx = divmod(int(ys[i, 0]), 2)
        xs[i, :, qy * 16:(qy + 1) * 16, qx * 16:(qx + 1) * 16] += 1.0
    losses = [float(exe.run(feed={"image": xs, "label": ys},
                            fetch_list=[loss])[0]) for _ in range(12)]
    assert losses[-1] < 0.7 * losses[0], losses[::4]
