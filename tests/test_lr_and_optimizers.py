"""LR schedulers (static + 2.0 classes) and the extended optimizer zoo.

Mirrors reference tests test_learning_rate_scheduler.py, test_lr_scheduler.py,
test_adadelta_op.py, test_ftrl_op.py, etc.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


@pytest.fixture(autouse=True)
def fresh_programs():
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    paddle.seed(0)
    yield


def _run_schedule(lr_var, steps):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    vals = []
    for _ in range(steps):
        v, = exe.run(feed={}, fetch_list=[lr_var])
        vals.append(float(v[0]))
    return vals


def test_static_exponential_decay():
    lr = layers.exponential_decay(0.1, decay_steps=2, decay_rate=0.5)
    got = _run_schedule(lr, 5)
    want = [0.1 * 0.5 ** (s / 2) for s in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_static_piecewise_decay():
    lr = layers.piecewise_decay([2, 4], [0.1, 0.01, 0.001])
    got = _run_schedule(lr, 6)
    np.testing.assert_allclose(got, [0.1, 0.1, 0.01, 0.01, 0.001, 0.001],
                               rtol=1e-6)


def test_static_noam_and_warmup():
    lr = layers.noam_decay(d_model=64, warmup_steps=4, learning_rate=1.0)
    got = _run_schedule(lr, 6)
    want = [64 ** -0.5 * min(s ** -0.5, s * 4 ** -1.5)
            for s in range(1, 7)]
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_static_linear_warmup_wraps_constant():
    lr = layers.linear_lr_warmup(0.1, warmup_steps=3, start_lr=0.0,
                                 end_lr=0.1)
    got = _run_schedule(lr, 5)
    np.testing.assert_allclose(
        got, [0.0, 0.1 / 3, 0.2 / 3, 0.1, 0.1], rtol=1e-5, atol=1e-7)


def test_static_cosine_polynomial_inverse_natural():
    lrs = {
        "cos": layers.cosine_decay(0.1, step_each_epoch=2, epochs=4),
        "poly": layers.polynomial_decay(0.1, decay_steps=4, end_learning_rate=0.01,
                                        power=2.0),
        "inv": layers.inverse_time_decay(0.1, decay_steps=1, decay_rate=0.5),
        "nat": layers.natural_exp_decay(0.1, decay_steps=1, decay_rate=0.5),
    }
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    names = list(lrs)
    rows = []
    for _ in range(4):
        rows.append([float(v[0]) for v in
                     exe.run(feed={}, fetch_list=[lrs[n] for n in names])])
    for s, row in enumerate(rows):
        got = dict(zip(names, row))
        epoch = s // 2
        assert got["cos"] == pytest.approx(
            0.05 * (math.cos(epoch * math.pi / 4) + 1), rel=1e-4)
        frac = min(s, 4) / 4
        assert got["poly"] == pytest.approx(
            (0.1 - 0.01) * (1 - frac) ** 2 + 0.01, rel=1e-4)
        assert got["inv"] == pytest.approx(0.1 / (1 + 0.5 * s), rel=1e-4)
        assert got["nat"] == pytest.approx(0.1 * math.exp(-0.5 * s), rel=1e-4)


def test_lr_scheduler_classes_math():
    from paddle_tpu.optimizer import lr
    s = lr.StepDecay(0.1, step_size=2, gamma=0.1)
    vals = [s()]
    for _ in range(3):
        s.step()
        vals.append(s())
    np.testing.assert_allclose(vals, [0.1, 0.1, 0.01, 0.01], rtol=1e-6)

    c = lr.CosineAnnealingDecay(0.1, T_max=10)
    assert c() == pytest.approx(0.1)
    m = lr.MultiStepDecay(0.1, milestones=[1, 3], gamma=0.5)
    m.step(), m.step()
    assert m() == pytest.approx(0.05)
    w = lr.LinearWarmup(lr.PiecewiseDecay([5], [0.1, 0.01]),
                        warmup_steps=2, start_lr=0.0, end_lr=0.1)
    assert w() == pytest.approx(0.0)
    w.step()
    assert w() == pytest.approx(0.05)
    w.step()
    assert w() == pytest.approx(0.1)

    r = lr.ReduceOnPlateau(0.1, patience=0, factor=0.5, cooldown=0)
    r.step(1.0)
    r.step(2.0)   # worse -> bad=1 > patience=0 -> reduce
    assert r() == pytest.approx(0.05)


def test_scheduler_drives_static_training():
    """LRScheduler bound to a static program: step() changes the LR var."""
    from paddle_tpu.optimizer import lr
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    sched = lr.StepDecay(0.5, step_size=1, gamma=0.1)
    opt = paddle.optimizer.SGD(learning_rate=sched)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    lr_name = opt._lr_var.name
    from paddle_tpu.framework.scope import global_scope
    sched._sync_static()
    assert float(np.asarray(global_scope().find(lr_name))[0]) == \
        pytest.approx(0.5)
    sched.step()
    assert float(np.asarray(global_scope().find(lr_name))[0]) == \
        pytest.approx(0.05)
    feed = {"x": np.ones((4, 1), np.float32), "y": np.zeros((4, 1), np.float32)}
    l0, = exe.run(feed=feed, fetch_list=[loss])
    assert np.isfinite(l0).all()


@pytest.mark.parametrize("make_opt", [
    lambda: paddle.optimizer.Adadelta(learning_rate=1.0),
    lambda: paddle.optimizer.DecayedAdagrad(learning_rate=0.5),
    lambda: paddle.optimizer.Ftrl(learning_rate=0.5),
    lambda: paddle.optimizer.DGCMomentumOptimizer(learning_rate=0.2,
                                                  momentum=0.9),
])
def test_new_optimizers_converge_quadratic(make_opt):
    from paddle_tpu.framework import program as pm, scope as sm, unique_name
    pm._main_program = pm.Program()
    pm._startup_program = pm.Program()
    sm._reset_global_scope()
    unique_name.switch()
    w = layers.create_parameter([4], "float32", name="w",
                                default_initializer=paddle.initializer.Constant(3.0))
    loss = layers.reduce_mean(layers.square(w))
    opt = make_opt()
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    l0, = exe.run(feed={}, fetch_list=[loss])
    for _ in range(50):
        lv, = exe.run(feed={}, fetch_list=[loss])
    assert float(lv) < float(l0) * 0.9, (float(l0), float(lv))


def test_lookahead_sync_moves_slow_weights():
    w = layers.create_parameter([2], "float32", name="w",
                                default_initializer=paddle.initializer.Constant(1.0))
    loss = layers.reduce_mean(layers.square(w))
    inner = paddle.optimizer.SGD(learning_rate=0.1)
    opt = paddle.optimizer.LookaheadOptimizer(inner, alpha=0.5, k=2)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    from paddle_tpu.framework.scope import global_scope
    for _ in range(4):
        exe.run(feed={}, fetch_list=[loss])
        opt.sync()
    wv = np.asarray(global_scope().find("w"))
    assert (np.abs(wv) < 1.0).all()   # moved toward 0
    assert np.isfinite(wv).all()


def test_dygraph_scheduler_with_adam():
    paddle.disable_static()
    try:
        import paddle_tpu.nn as nn
        from paddle_tpu.optimizer import lr
        lin = nn.Linear(3, 1)
        sched = lr.ExponentialDecay(0.1, gamma=0.5)
        opt = paddle.optimizer.Adam(learning_rate=sched,
                                    parameter_list=list(lin.parameters()))
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        for i in range(3):
            loss = paddle.tensor.mean(lin(x))
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
        assert sched() == pytest.approx(0.1 * 0.5 ** 3)
    finally:
        paddle.enable_static()
