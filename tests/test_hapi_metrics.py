"""hapi Model.fit/evaluate/predict + paddle.metric + callbacks.

Mirrors reference tests test_model.py, test_metrics.py, test_callbacks.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture(autouse=True)
def dygraph_mode():
    paddle.disable_static()
    yield
    paddle.enable_static()


class XorNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(2, 16)
        self.fc2 = nn.Linear(16, 2)

    def forward(self, x):
        return self.fc2(nn.functional.tanh(self.fc1(x)))


class XorData(paddle.io.Dataset):
    def __init__(self, n=128):
        rng = np.random.RandomState(0)
        self.x = rng.randint(0, 2, (n, 2)).astype(np.float32)
        self.y = (self.x[:, :1] != self.x[:, 1:2]).astype(np.int64)
        self.x += rng.randn(n, 2).astype(np.float32) * 0.05

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def test_model_fit_evaluate_predict_save_load(tmp_path):
    model = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])],
                         labels=[paddle.hapi.Input([1], "int64")])
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.05),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    train = XorData(128)
    model.fit(train, epochs=20, batch_size=32, verbose=0)
    logs = model.evaluate(XorData(64), batch_size=32, verbose=0)
    assert logs["acc"] > 0.9, logs
    assert logs["loss"] < 0.5

    preds = model.predict(XorData(16), batch_size=8, stack_outputs=True)
    assert preds[0].shape == (16, 2)

    path = str(tmp_path / "xor" / "model")
    model.save(path)
    fresh = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])])
    fresh.prepare(loss=nn.CrossEntropyLoss(),
                  metrics=paddle.metric.Accuracy())
    fresh.load(path)
    logs2 = fresh.evaluate(XorData(64), batch_size=32, verbose=0)
    assert logs2["acc"] == pytest.approx(logs["acc"], abs=0.05)


def test_early_stopping_stops(tmp_path):
    model = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])],
                         labels=[paddle.hapi.Input([1], "int64")])
    model.prepare(optimizer=paddle.optimizer.Adam(learning_rate=0.05),
                  loss=nn.CrossEntropyLoss())
    stopper = paddle.hapi.EarlyStopping(monitor="loss", patience=0,
                                        mode="min")
    model.fit(XorData(64), eval_data=XorData(32), epochs=50, batch_size=32,
              verbose=0, callbacks=[stopper])
    assert stopper.stopped or not model.stop_training  # stopped early OR ran out
    # the fit must not have run all 50 epochs unless loss kept improving
    assert stopper.best is not None


def test_model_checkpoint_saves(tmp_path):
    model = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])],
                         labels=[paddle.hapi.Input([1], "int64")])
    model.prepare(optimizer=paddle.optimizer.Adam(learning_rate=0.05),
                  loss=nn.CrossEntropyLoss())
    model.fit(XorData(32), epochs=2, batch_size=16, verbose=0,
              save_dir=str(tmp_path / "ckpt"))
    import os
    assert os.path.exists(tmp_path / "ckpt" / "final.pdparams")
    assert os.path.exists(tmp_path / "ckpt" / "0.pdparams")


def test_metric_accuracy_topk():
    m = paddle.metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.6, 0.3, 0.1]], np.float32)
    label = np.array([[1], [2]])
    correct = m.compute(pred, label)
    m.update(correct)
    acc1, acc2 = m.accumulate()
    assert acc1 == pytest.approx(0.5)   # row0 top1 correct, row1 wrong
    assert acc2 == pytest.approx(0.5)   # row1's label 2 not in top2
    m.reset()
    assert m.accumulate() == [0.0, 0.0] or m.accumulate() == 0.0


def test_metric_precision_recall_auc():
    p = paddle.metric.Precision()
    r = paddle.metric.Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)
    assert r.accumulate() == pytest.approx(2 / 3)

    auc = paddle.metric.Auc()
    rng = np.random.RandomState(0)
    pos = np.clip(rng.normal(0.7, 0.1, 500), 0, 1)
    neg = np.clip(rng.normal(0.3, 0.1, 500), 0, 1)
    auc.update(np.concatenate([pos, neg]),
               np.concatenate([np.ones(500), np.zeros(500)]))
    assert auc.accumulate() > 0.95


def test_summary_counts_params():
    paddle.enable_static()  # summary is mode-agnostic; exercise re-entry too
    paddle.disable_static()
    model = paddle.Model(XorNet())
    info = model.summary()
    assert info["total_params"] == 2 * 16 + 16 + 16 * 2 + 2


def test_auc_anchor_at_origin():
    auc = paddle.metric.Auc()
    auc.update(np.ones(10), np.array([1, 0] * 5))
    assert auc.accumulate() == pytest.approx(0.5)


def test_model_save_restores_optimizer_state(tmp_path):
    model = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])],
                         labels=[paddle.hapi.Input([1], "int64")])
    opt = paddle.optimizer.Adam(learning_rate=0.05)
    model.prepare(opt, nn.CrossEntropyLoss())
    model.fit(XorData(32), epochs=2, batch_size=16, verbose=0)
    path = str(tmp_path / "m" / "ck")
    model.save(path)
    assert opt.state_dict(), "dygraph Adam must expose accumulators"

    model2 = paddle.Model(XorNet(), inputs=[paddle.hapi.Input([2])],
                          labels=[paddle.hapi.Input([1], "int64")])
    opt2 = paddle.optimizer.Adam(learning_rate=0.05)
    model2.prepare(opt2, nn.CrossEntropyLoss())
    model2.load(path)
    sd1 = opt.state_dict()
    sd2 = opt2.state_dict()
    assert set(sd1) == set(sd2)
    for k in sd1:
        np.testing.assert_allclose(np.asarray(sd2[k]), np.asarray(sd1[k]),
                                   rtol=1e-6)
