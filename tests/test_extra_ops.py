"""OpTest coverage for the extra-op batch (losses/linalg/rearrangement).

Reference analog: per-op unittests (test_bce_loss_op.py, test_kron_op.py,
test_pixel_shuffle.py, ... in fluid/tests/unittests/) — numpy reference
outputs + finite-difference grad checks via the op_test harness."""
import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (registers all ops)
from op_test import check_output, check_grad, run_op

R = np.random.RandomState(0)


def test_bce_loss():
    x = R.uniform(0.05, 0.95, (4, 5)).astype(np.float32)
    lb = R.randint(0, 2, (4, 5)).astype(np.float32)
    ref = -(lb * np.log(x) + (1 - lb) * np.log(1 - x))
    check_output("bce_loss", {"X": [x], "Label": [lb]}, {}, {"Out": [ref]},
                 rtol=1e-4, atol=1e-5)
    check_grad("bce_loss", {"X": [x], "Label": [lb]}, {}, wrt=["X"])


def test_hinge_loss():
    x = R.randn(6, 1).astype(np.float32)
    y = R.randint(0, 2, (6, 1)).astype(np.float32)
    ref = np.maximum(1 - (2 * y - 1) * x, 0)
    check_output("hinge_loss", {"Logits": [x], "Labels": [y]}, {},
                 {"Loss": [ref]}, rtol=1e-5, atol=1e-6)


def test_rank_loss():
    lbl = R.randint(0, 2, (5, 1)).astype(np.float32)
    left = R.randn(5, 1).astype(np.float32)
    right = R.randn(5, 1).astype(np.float32)
    o = left - right
    ref = np.log1p(np.exp(o)) - lbl * o
    check_output("rank_loss", {"Label": [lbl], "Left": [left],
                               "Right": [right]}, {}, {"Out": [ref]},
                 rtol=1e-5, atol=1e-6)
    check_grad("rank_loss", {"Label": [lbl], "Left": [left],
                             "Right": [right]}, {}, wrt=["Left", "Right"])


def test_log_loss():
    p = R.uniform(0.1, 0.9, (8, 1)).astype(np.float32)
    y = R.randint(0, 2, (8, 1)).astype(np.float32)
    eps = 1e-4
    ref = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
    check_output("log_loss", {"Predicted": [p], "Labels": [y]},
                 {"epsilon": eps}, {"Loss": [ref]}, rtol=1e-5, atol=1e-6)


def test_bpr_loss():
    x = R.randn(4, 6).astype(np.float32)
    lbl = R.randint(0, 6, (4, 1)).astype(np.int64)
    ref = np.zeros((4, 1), np.float64)
    for i in range(4):
        l = lbl[i, 0]
        s = sum(np.log1p(np.exp(x[i, j] - x[i, l]))
                for j in range(6) if j != l)
        ref[i, 0] = s / 5
    check_output("bpr_loss", {"X": [x], "Label": [lbl]}, {}, {"Y": [ref]},
                 rtol=1e-4, atol=1e-5)


def test_nll_loss_mean_and_none():
    x = np.log(R.dirichlet(np.ones(5), 6)).astype(np.float32)
    lbl = R.randint(0, 5, (6,)).astype(np.int64)
    picked = -x[np.arange(6), lbl]
    check_output("nll_loss", {"X": [x], "Label": [lbl]},
                 {"reduction": "mean"}, {"Out": [picked.mean()]},
                 rtol=1e-5, atol=1e-6)
    check_output("nll_loss", {"X": [x], "Label": [lbl]},
                 {"reduction": "none"}, {"Out": [picked]},
                 rtol=1e-5, atol=1e-6)


def test_kldiv_loss():
    x = np.log(R.dirichlet(np.ones(4), 5)).astype(np.float32)
    t = R.dirichlet(np.ones(4), 5).astype(np.float32)
    ref = (t * (np.log(t) - x)).mean()
    check_output("kldiv_loss", {"X": [x], "Target": [t]},
                 {"reduction": "mean"}, {"Loss": [ref]},
                 rtol=1e-4, atol=1e-5)
    check_grad("kldiv_loss", {"X": [x], "Target": [t]},
               {"reduction": "mean"}, wrt=["X"], out_slots=("Loss",))


def test_smooth_l1_loss():
    x = R.randn(3, 4).astype(np.float32)
    y = R.randn(3, 4).astype(np.float32)
    d = x - y
    ad = np.abs(d)
    elem = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
    ref = elem.sum(axis=1, keepdims=True)
    check_output("smooth_l1_loss", {"X": [x], "Y": [y]}, {"sigma": 1.0},
                 {"Out": [ref], "Diff": [d]}, rtol=1e-4, atol=1e-5)
    check_grad("smooth_l1_loss", {"X": [x], "Y": [y]}, {"sigma": 1.0},
               wrt=["X"], out_slots=("Out",))


def test_addmm_mv_kron_cross_trace():
    a = R.randn(3, 5).astype(np.float32)
    x = R.randn(3, 4).astype(np.float32)
    y = R.randn(4, 5).astype(np.float32)
    check_output("addmm", {"Input": [a], "X": [x], "Y": [y]},
                 {"Alpha": 2.0, "Beta": 0.5}, {"Out": [0.5 * a + 2 * x @ y]},
                 rtol=1e-4, atol=1e-5)
    v = R.randn(4).astype(np.float32)
    check_output("mv", {"X": [x], "Vec": [v]}, {}, {"Out": [x @ v]},
                 rtol=1e-4, atol=1e-5)
    check_output("kron", {"X": [x], "Y": [y]}, {}, {"Out": [np.kron(x, y)]},
                 rtol=1e-4, atol=1e-5)
    c1 = R.randn(4, 3).astype(np.float32)
    c2 = R.randn(4, 3).astype(np.float32)
    check_output("cross", {"X": [c1], "Y": [c2]}, {"dim": 1},
                 {"Out": [np.cross(c1, c2, axis=1)]}, rtol=1e-4, atol=1e-5)
    m = R.randn(5, 5).astype(np.float32)
    check_output("trace", {"Input": [m]}, {}, {"Out": [np.trace(m)]},
                 rtol=1e-4, atol=1e-5)


def test_cholesky_inverse_matrix_power():
    a = R.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    check_output("cholesky", {"X": [spd]}, {"upper": False},
                 {"Out": [np.linalg.cholesky(spd)]}, rtol=1e-3, atol=1e-4)
    check_output("inverse", {"Input": [spd]}, {},
                 {"Output": [np.linalg.inv(spd)]}, rtol=1e-3, atol=1e-4)
    check_output("matrix_power", {"X": [spd]}, {"n": 3},
                 {"Out": [np.linalg.matrix_power(spd, 3)]},
                 rtol=1e-3, atol=1e-2)


def test_dist_norms():
    x = R.randn(3, 4).astype(np.float32)
    y = R.randn(3, 4).astype(np.float32)
    check_output("dist", {"X": [x], "Y": [y]}, {"p": 2.0},
                 {"Out": [np.linalg.norm((x - y).ravel())]},
                 rtol=1e-4, atol=1e-5)
    check_output("frobenius_norm", {"X": [x]}, {"reduce_all": True},
                 {"Out": [np.sqrt((x * x).sum())]}, rtol=1e-4, atol=1e-5)
    check_output("l1_norm", {"X": [x]}, {}, {"Out": [np.abs(x).sum()]},
                 rtol=1e-4, atol=1e-5)
    from scipy.special import logsumexp as np_lse
    check_output("logsumexp", {"X": [x]}, {"axis": [1], "keepdim": False},
                 {"Out": [np_lse(x, axis=1)]}, rtol=1e-4, atol=1e-5)
    nrm = np.sqrt((x * x).sum(axis=1, keepdims=True) + 1e-10)
    check_output("norm", {"X": [x]}, {"axis": 1},
                 {"Out": [x / nrm], "Norm": [nrm]}, rtol=1e-4, atol=1e-5)


def test_cos_sim():
    x = R.randn(4, 6).astype(np.float32)
    y = R.randn(4, 6).astype(np.float32)
    xn = np.linalg.norm(x, axis=1, keepdims=True)
    yn = np.linalg.norm(y, axis=1, keepdims=True)
    ref = (x * y).sum(1, keepdims=True) / (xn * yn + 1e-12)
    check_output("cos_sim", {"X": [x], "Y": [y]}, {}, {"Out": [ref]},
                 rtol=1e-4, atol=1e-5)


def test_index_sample_multiplex():
    x = R.randn(4, 8).astype(np.float32)
    idx = R.randint(0, 8, (4, 3)).astype(np.int64)
    ref = np.take_along_axis(x, idx, axis=1)
    check_output("index_sample", {"X": [x], "Index": [idx]}, {},
                 {"Out": [ref]}, rtol=1e-5, atol=1e-6)
    xs = [R.randn(5, 3).astype(np.float32) for _ in range(4)]
    ids = R.randint(0, 4, (5, 1)).astype(np.int64)
    ref2 = np.stack([xs[ids[i, 0]][i] for i in range(5)])
    check_output("multiplex", {"X": xs, "Ids": [ids]}, {}, {"Out": [ref2]},
                 rtol=1e-5, atol=1e-6)


def test_scatter_nd_add():
    x = np.zeros((4, 5), np.float32)
    index = np.array([[1, 1], [2, 3], [1, 1]], np.int64)
    upd = np.array([1.0, 2.0, 3.0], np.float32)
    ref = x.copy()
    for (i, j), u in zip(index, upd):
        ref[i, j] += u
    check_output("scatter_nd_add", {"X": [x], "Index": [index],
                                    "Updates": [upd]}, {}, {"Out": [ref]},
                 rtol=1e-5, atol=1e-6)


def test_rearrangement_ops():
    x = R.randn(2, 8, 4, 6).astype(np.float32)
    out = run_op("pixel_shuffle", {"X": [x]}, {"upscale_factor": 2})
    assert out["Out"][0].shape == (2, 2, 8, 12)
    out = run_op("space_to_depth", {"X": [x]}, {"blocksize": 2})
    assert out["Out"][0].shape == (2, 32, 2, 3)
    # round trip property: space_to_depth then pixel_shuffle ~ identity-ish
    sc = run_op("shuffle_channel", {"X": [x]}, {"group": 2})["Out"][0]
    assert np.asarray(sc).shape == x.shape
    np.testing.assert_allclose(np.asarray(sc)[:, 0], x[:, 0])
    np.testing.assert_allclose(np.asarray(sc)[:, 1], x[:, 4])
    rev = run_op("reverse", {"X": [x]}, {"axis": [1]})["Out"][0]
    np.testing.assert_allclose(np.asarray(rev), x[:, ::-1])
    ub = run_op("unbind", {"X": [x]}, {"axis": 1})["Out"]
    assert len(ub) == 8 and np.allclose(np.asarray(ub[3]), x[:, 3])


def test_temporal_shift():
    x = R.randn(6, 4, 2, 2).astype(np.float32)   # N=3 segments of T=2
    out = np.asarray(run_op("temporal_shift", {"X": [x]},
                            {"seg_num": 2, "shift_ratio": 0.25})["Out"][0])
    x5 = x.reshape(3, 2, 4, 2, 2)
    # c1=1 shifted back: out[:, t, 0] = x[:, t+1, 0]
    np.testing.assert_allclose(out.reshape(3, 2, 4, 2, 2)[:, 0, 0],
                               x5[:, 1, 0])
    # c1..c2 shifted forward: out[:, 1, 1] = x[:, 0, 1]
    np.testing.assert_allclose(out.reshape(3, 2, 4, 2, 2)[:, 1, 1],
                               x5[:, 0, 1])


def test_unfold_matches_manual_im2col():
    x = R.randn(2, 3, 5, 5).astype(np.float32)
    out = np.asarray(run_op("unfold", {"X": [x]},
                            {"kernel_sizes": [3, 3], "strides": [1, 1],
                             "paddings": [0, 0], "dilations": [1, 1]})["Y"][0])
    assert out.shape == (2, 27, 9)
    # spot check one patch: output column 0 = x[:, :, 0:3, 0:3] flattened
    np.testing.assert_allclose(out[0, :, 0],
                               x[0, :, 0:3, 0:3].reshape(-1), rtol=1e-5)


def test_affine_channel_prelu_selu_mish():
    x = R.randn(2, 3, 4, 4).astype(np.float32)
    s = R.randn(3).astype(np.float32)
    b = R.randn(3).astype(np.float32)
    ref = x * s[None, :, None, None] + b[None, :, None, None]
    check_output("affine_channel", {"X": [x], "Scale": [s], "Bias": [b]},
                 {}, {"Out": [ref]}, rtol=1e-5, atol=1e-6)
    a = np.array([0.25], np.float32)
    ref2 = np.where(x > 0, x, 0.25 * x)
    check_output("prelu", {"X": [x], "Alpha": [a]}, {"mode": "all"},
                 {"Out": [ref2]}, rtol=1e-5, atol=1e-6)
    check_grad("mish", {"X": [R.randn(3, 4).astype(np.float32)]}, {},
               wrt=["X"])
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    ref3 = scale * np.where(x > 0, x, alpha * (np.exp(x) - 1))
    check_output("selu", {"X": [x]}, {}, {"Out": [ref3]},
                 rtol=1e-4, atol=1e-5)


def test_label_smooth_shard_index_cvm():
    oh = np.eye(4, dtype=np.float32)[R.randint(0, 4, 5)]
    ref = 0.9 * oh + 0.1 / 4
    check_output("label_smooth", {"X": [oh]}, {"epsilon": 0.1},
                 {"Out": [ref]}, rtol=1e-5, atol=1e-6)
    ids = np.array([[1], [5], [9], [3]], np.int64)
    out = np.asarray(run_op("shard_index", {"X": [ids]},
                            {"index_num": 10, "nshards": 2, "shard_id": 0,
                             "ignore_value": -1})["Out"][0])
    np.testing.assert_array_equal(out, [[1], [-1], [-1], [3]])
    x = np.abs(R.randn(3, 6)).astype(np.float32)
    out = np.asarray(run_op("cvm", {"X": [x]}, {"use_cvm": True})["Y"][0])
    np.testing.assert_allclose(out[:, 0], np.log(x[:, 0] + 1), rtol=1e-5)


def test_lrn_and_grid_sampler_shapes():
    x = R.randn(2, 7, 3, 3).astype(np.float32)
    out = run_op("lrn", {"X": [x]}, {"n": 5, "k": 2.0, "alpha": 1e-4,
                                     "beta": 0.75})
    assert out["Out"][0].shape == x.shape
    # channel 0 accumulates channels 0..2 (window center semantics)
    mid = np.asarray(out["MidOut"][0])
    acc0 = (x[:, 0:3] ** 2).sum(axis=1)
    np.testing.assert_allclose(mid[:, 0], 2.0 + 1e-4 * acc0, rtol=1e-5)

    g = np.zeros((2, 3, 3, 2), np.float32)   # identity-ish grid center
    img = R.randn(2, 4, 3, 3).astype(np.float32)
    out = np.asarray(run_op("grid_sampler", {"X": [img], "Grid": [g]},
                            {})["Output"][0])
    # grid of zeros samples the center pixel everywhere
    np.testing.assert_allclose(out[:, :, 1, 1], img[:, :, 1, 1], rtol=1e-5)
    assert out.shape == (2, 4, 3, 3)


def test_conv3d_pool3d():
    x = R.randn(1, 2, 4, 4, 4).astype(np.float32)
    w = R.randn(3, 2, 2, 2, 2).astype(np.float32)
    out = run_op("conv3d", {"Input": [x], "Filter": [w]},
                 {"strides": [1, 1, 1], "paddings": [0, 0, 0]})
    assert out["Output"][0].shape == (1, 3, 3, 3, 3)
    p = run_op("pool3d", {"X": [x]}, {"pooling_type": "max",
                                      "ksize": [2, 2, 2],
                                      "strides": [2, 2, 2],
                                      "paddings": [0, 0, 0]})
    ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(np.asarray(p["Out"][0]), ref, rtol=1e-5)


def test_max_pool2d_with_index():
    x = R.randn(1, 1, 4, 4).astype(np.float32)
    out = run_op("max_pool2d_with_index", {"X": [x]},
                 {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]})
    vals = np.asarray(out["Out"][0])
    mask = np.asarray(out["Mask"][0])
    for oy in range(2):
        for ox in range(2):
            patch = x[0, 0, oy*2:oy*2+2, ox*2:ox*2+2]
            assert vals[0, 0, oy, ox] == patch.max()
            iy, ix = np.unravel_index(patch.argmax(), (2, 2))
            assert mask[0, 0, oy, ox] == (oy*2 + iy) * 4 + (ox*2 + ix)


def test_segment_pool():
    x = R.randn(6, 3).astype(np.float32)
    seg = np.array([0, 0, 1, 1, 1, 2], np.int64)
    out = np.asarray(run_op("segment_pool", {"X": [x], "SegmentIds": [seg]},
                            {"pooltype": "MEAN", "num_segments": 3})["Out"][0])
    np.testing.assert_allclose(out[1], x[2:5].mean(axis=0), rtol=1e-5)


def test_spectral_norm():
    w = R.randn(4, 6).astype(np.float32)
    u = R.randn(4).astype(np.float32)
    v = R.randn(6).astype(np.float32)
    out = np.asarray(run_op("spectral_norm",
                            {"Weight": [w], "U": [u], "V": [v]},
                            {"dim": 0, "power_iters": 20})["Out"][0])
    # after many power iters, the top singular value of out is ~1
    assert abs(np.linalg.svd(out, compute_uv=False)[0] - 1.0) < 1e-3


def test_data_norm():
    x = R.randn(5, 3).astype(np.float32)
    size = np.full((3,), 10.0, np.float32)
    bsum = R.randn(3).astype(np.float32) * 10
    bsq = np.abs(R.randn(3)).astype(np.float32) * 10 + bsum ** 2 / 10 + 5
    out = run_op("data_norm", {"X": [x], "BatchSize": [size],
                               "BatchSum": [bsum], "BatchSquareSum": [bsq]},
                 {"epsilon": 1e-4})
    means = bsum / size
    scales = np.sqrt(size / bsq)   # reference data_norm_op.cc:301-302
    np.testing.assert_allclose(np.asarray(out["Y"][0]), (x - means) * scales,
                               rtol=1e-4, atol=1e-5)


def test_pad_ops():
    x = R.randn(1, 1, 2, 3, 3).astype(np.float32)
    out = run_op("pad3d", {"X": [x]}, {"paddings": [1, 1, 0, 0, 0, 0],
                                       "mode": "constant", "value": 0.0})
    assert out["Out"][0].shape == (1, 1, 2, 3, 5)
    big = R.randn(4, 5).astype(np.float32)
    small = R.randn(2, 3).astype(np.float32)
    out = np.asarray(run_op("pad_constant_like",
                            {"X": [big], "Y": [small]},
                            {"pad_value": 7.0})["Out"][0])
    assert out.shape == (4, 5) and out[3, 4] == 7.0
    np.testing.assert_allclose(out[:2, :3], small)


def test_sigmoid_focal_loss_and_center_loss():
    x = R.randn(5, 3).astype(np.float32)
    lbl = R.randint(0, 4, (5, 1)).astype(np.int64)   # 0 = background
    fg = np.array([3], np.int64)
    out = run_op("sigmoid_focal_loss",
                 {"X": [x], "Label": [lbl], "FgNum": [fg]},
                 {"gamma": 2.0, "alpha": 0.25})
    assert out["Out"][0].shape == (5, 3)
    assert np.isfinite(np.asarray(out["Out"][0])).all()

    feat = R.randn(6, 4).astype(np.float32)
    labels = R.randint(0, 3, (6,)).astype(np.int64)
    centers = R.randn(3, 4).astype(np.float32)
    out = run_op("center_loss", {"X": [feat], "Label": [labels],
                                 "Centers": [centers]},
                 {"alpha": 0.1, "need_update": True})
    diff = feat - centers[labels]
    np.testing.assert_allclose(np.asarray(out["Loss"][0]),
                               0.5 * (diff ** 2).sum(1, keepdims=True),
                               rtol=1e-4, atol=1e-5)
    assert not np.allclose(np.asarray(out["CentersOut"][0]), centers)


def test_activation_tail():
    x = R.randn(4, 5).astype(np.float32)
    check_output("hard_shrink", {"X": [x]}, {"threshold": 0.5},
                 {"Out": [np.where(np.abs(x) > 0.5, x, 0)]},
                 rtol=1e-5, atol=1e-6)
    check_output("softshrink", {"X": [x]}, {"lambda": 0.5},
                 {"Out": [np.where(x > 0.5, x - 0.5,
                                   np.where(x < -0.5, x + 0.5, 0))]},
                 rtol=1e-5, atol=1e-6)
    check_output("tanh_shrink", {"X": [x]}, {}, {"Out": [x - np.tanh(x)]},
                 rtol=1e-5, atol=1e-6)
    check_output("thresholded_relu", {"X": [x]}, {"threshold": 0.3},
                 {"Out": [np.where(x > 0.3, x, 0)]}, rtol=1e-5, atol=1e-6)
    check_output("stanh", {"X": [x]}, {"scale_a": 0.67, "scale_b": 1.7159},
                 {"Out": [1.7159 * np.tanh(0.67 * x)]}, rtol=1e-5, atol=1e-6)
    check_grad("celu", {"X": [x]}, {"alpha": 1.2}, wrt=["X"])
    m = R.randn(2, 6, 3, 3).astype(np.float32)
    ref = m.reshape(2, 3, 2, 3, 3).max(axis=2)
    check_output("maxout", {"X": [m]}, {"groups": 2}, {"Out": [ref]},
                 rtol=1e-5, atol=1e-6)


def test_misc_tail():
    x = R.randn(3, 4).astype(np.float32)
    y = R.randn(3, 4).astype(np.float32)
    check_output("minus", {"X": [x], "Y": [y]}, {}, {"Out": [x - y]},
                 rtol=1e-5, atol=1e-6)
    xs = [R.randn(3, 6).astype(np.float32) for _ in range(2)]
    check_output("partial_concat", {"X": xs},
                 {"start_index": 1, "length": 2},
                 {"Out": [np.concatenate([xs[0][:, 1:3], xs[1][:, 1:3]], 1)]},
                 rtol=1e-5, atol=1e-6)
    check_output("partial_sum", {"X": xs}, {"start_index": 1, "length": 2},
                 {"Out": [xs[0][:, 1:3] + xs[1][:, 1:3]]},
                 rtol=1e-5, atol=1e-6)
    d = R.randn(5).astype(np.float32)
    check_output("diag", {"Diagonal": [d]}, {}, {"Out": [np.diag(d)]},
                 rtol=1e-5, atol=1e-6)
    check_output("diag_v2", {"X": [d]}, {"offset": 0}, {"Out": [np.diag(d)]},
                 rtol=1e-5, atol=1e-6)
    m = R.randn(4, 4).astype(np.float32)
    check_output("diag_v2", {"X": [m]}, {"offset": 1},
                 {"Out": [np.diagonal(m, offset=1)]}, rtol=1e-5, atol=1e-6)
    de = run_op("diag_embed", {"Input": [d]}, {"offset": 0})["Out"][0]
    np.testing.assert_allclose(np.asarray(de), np.diag(d), rtol=1e-6)


def test_rnn_units():
    h = 4
    x4 = R.randn(3, 4 * h).astype(np.float32)
    c_prev = R.randn(3, h).astype(np.float32)
    out = run_op("lstm_unit", {"X": [x4], "C_prev": [c_prev]},
                 {"forget_bias": 0.0})
    sig = lambda v: 1 / (1 + np.exp(-v))
    i, f = sig(x4[:, :h]), sig(x4[:, h:2*h])
    g, o = np.tanh(x4[:, 2*h:3*h]), sig(x4[:, 3*h:])
    c_ref = f * c_prev + i * g
    np.testing.assert_allclose(np.asarray(out["C"][0]), c_ref,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["H"][0]), o * np.tanh(c_ref),
                               rtol=1e-4, atol=1e-5)

    x3 = R.randn(3, 3 * h).astype(np.float32)
    hp = R.randn(3, h).astype(np.float32)
    w = R.randn(h, 3 * h).astype(np.float32)
    out = run_op("gru_unit", {"Input": [x3], "HiddenPrev": [hp],
                              "Weight": [w]}, {})
    assert out["Hidden"][0].shape == (3, h)
    assert np.isfinite(np.asarray(out["Hidden"][0])).all()


def test_row_conv_and_im2sequence():
    x = R.randn(2, 5, 3).astype(np.float32)
    w = R.randn(2, 3).astype(np.float32)
    out = np.asarray(run_op("row_conv", {"X": [x], "Filter": [w]},
                            {})["Out"][0])
    ref_t0 = x[:, 0] * w[0] + x[:, 1] * w[1]
    np.testing.assert_allclose(out[:, 0], ref_t0, rtol=1e-4, atol=1e-5)
    ref_last = x[:, 4] * w[0]   # lookahead padded with zeros
    np.testing.assert_allclose(out[:, 4], ref_last, rtol=1e-4, atol=1e-5)

    img = R.randn(2, 3, 4, 4).astype(np.float32)
    seq = np.asarray(run_op("im2sequence", {"X": [img]},
                            {"kernels": [2, 2], "strides": [2, 2],
                             "paddings": [0, 0, 0, 0]})["Out"][0])
    assert seq.shape == (2 * 2 * 2, 3 * 2 * 2)


def test_warpctc_loss_finite_and_positive():
    logits = R.randn(2, 8, 5).astype(np.float32)
    labels = R.randint(1, 5, (2, 3)).astype(np.int32)
    llen = np.array([8, 6], np.int64)
    tlen = np.array([3, 2], np.int64)
    out = np.asarray(run_op("warpctc", {"Logits": [logits],
                                        "Label": [labels],
                                        "LogitsLength": [llen],
                                        "LabelLength": [tlen]},
                            {"blank": 0})["Loss"][0])
    assert out.shape == (2, 1) and (out > 0).all() and np.isfinite(out).all()


def test_cross_entropy2_and_fsp():
    p = R.dirichlet(np.ones(4), 6).astype(np.float32)
    lbl = R.randint(0, 4, (6, 1)).astype(np.int64)
    out = np.asarray(run_op("cross_entropy2", {"X": [p], "Label": [lbl]},
                            {})["Y"][0])
    ref = -np.log(p[np.arange(6), lbl[:, 0]])[:, None]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    a = R.randn(2, 3, 4, 4).astype(np.float32)
    b = R.randn(2, 5, 4, 4).astype(np.float32)
    out = np.asarray(run_op("fsp", {"X": [a], "Y": [b]}, {})["Out"][0])
    ref = np.einsum("nxs,nys->nxy", a.reshape(2, 3, 16),
                    b.reshape(2, 5, 16)) / 16
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_unpool_roundtrip():
    x = R.randn(1, 2, 4, 4).astype(np.float32)
    p = run_op("max_pool2d_with_index", {"X": [x]},
               {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]})
    up = np.asarray(run_op("unpool", {"X": [p["Out"][0]],
                                      "Indices": [p["Mask"][0]]},
                           {"ksize": [2, 2], "output_height": 4,
                            "output_width": 4})["Out"][0])
    # unpooled map has the max values at their original positions
    mask = up != 0
    np.testing.assert_allclose(up[mask], x[0][mask[0]], rtol=1e-6)
