"""Profiler/timeline, flags, NaN-Inf debug, monitor stats.

Mirrors reference tests test_profiler.py, test_nan_inf.py and the
platform/monitor.h stat registry behavior.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_profiler_collects_spans_and_exports_timeline(tmp_path):
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    loss = layers.reduce_mean(layers.square(layers.fc(x, size=4)))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    paddle.profiler.reset_profiler()
    path = str(tmp_path / "timeline.json")
    with fluid.profiler.profiler(profile_path=path):
        for _ in range(3):
            exe.run(feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss])
    with open(path) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert len(names) >= 3
    assert any("executor_run" in n for n in names)
    # complete ("X") spans carry ts+dur; the export may also include
    # thread-name metadata ("M") and instant/flow events (no dur)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert spans and all("ts" in e and "dur" in e for e in spans)
    # lanes are labeled with REAL thread ids + name metadata (process_*
    # metadata — rank/role lane labels — rides along without tids)
    metas = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
    assert any(e["name"] == "thread_name" for e in metas)
    assert any(e["name"] == "process_name" for e in metas)
    span_tids = {e["tid"] for e in spans}
    assert span_tids <= {e["tid"] for e in metas
                         if e["name"] == "thread_name"}


def test_flags_set_get_and_env_rejects_unknown():
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] \
        is False
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(KeyError, match="unknown flag"):
        paddle.set_flags({"FLAGS_not_a_flag": 1})


def test_check_nan_inf_names_the_variable():
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    bad = layers.log(x)  # log of negative -> nan
    exe = fluid.Executor()
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError, match=bad.name):
            exe.run(feed={"x": -np.ones((1, 2), np.float32)},
                    fetch_list=[bad])
        # warn-only level
        paddle.set_flags({"FLAGS_check_nan_inf_level": 1})
        with pytest.warns(UserWarning, match="NaN/Inf"):
            exe.run(feed={"x": -np.ones((1, 2), np.float32)},
                    fetch_list=[bad])
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False,
                          "FLAGS_check_nan_inf_level": 0})


def test_monitor_stats():
    paddle.monitor.stat_reset()
    paddle.monitor.stat_add("reader_queue_size", 5)
    paddle.monitor.stat_add("reader_queue_size", 3)
    assert paddle.monitor.stat_get("reader_queue_size") == 8
    paddle.monitor.stat_set("high_watermark", 123)
    assert paddle.monitor.all_stats()["high_watermark"] == 123
    paddle.monitor.stat_reset("high_watermark")
    assert paddle.monitor.stat_get("high_watermark") == 0
    # device stats shape only (may be empty off-TPU)
    assert isinstance(paddle.monitor.device_memory_stats(), dict)
