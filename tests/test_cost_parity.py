"""Prediction-vs-census parity: the compile-free cost model against the
real compiled step (ISSUE 13).

`analysis.predict_cost` claims EXACT collective prediction (kind + HLO
instruction count, bytes within 1%) on the manual-dp rows — every
collective there is placed by this repo's own passes — and per-device
argument/output memory within 5% of XLA's `compiled_memory_analysis`
everywhere. This suite pins that contract across six mesh/stage points
(dp=2 replicated / zero1 / zero2-bucketed / zero3-rolled, dp=4, dp=2×tp=2)
in ONE subprocess on the virtual CPU mesh: the prediction runs BEFORE the
Executor exists (zero compiles by the analysis itself), then the step
compiles and the census must match.

The dp×tp row is the honesty check on the OTHER side of the contract:
GSPMD owns collective placement there, so the report must say
`exact=False`, predict only kinds GSPMD really emits, and still nail the
memory model.

`scripts/collective_audit.py --assert` derives its dp/ZeRO budget rows
from the same predictor, so this suite failing means the CI budget just
lost its expected-count source — fix the predictor or the pass, never
the tolerance.
"""
import json
import os
import subprocess
import sys
import textwrap

from conftest import cpu_mesh_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARITY = """
import json
import numpy as np
import jax
import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import analysis
from paddle_tpu.distributed import fleet
from paddle_tpu.models import bert
from paddle_tpu.parallel import build_mesh, DistConfig, attach
from paddle_tpu.parallel.mesh import ShardingRules
from paddle_tpu.testing import reset_programs

import importlib.util, os
_repo = os.path.dirname(os.path.dirname(os.path.abspath(
    __import__("paddle_tpu").__file__)))
_spec = importlib.util.spec_from_file_location(
    "collective_audit", os.path.join(_repo, "scripts",
                                     "collective_audit.py"))
_audit = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_audit)

def run_row(axes, stage=0, bucket_mb=None, layer_scan=False,
            tp_rules=False, batch=16):
    reset_programs(seed=0)
    cfg = bert.BertConfig(vocab_size=256, hidden_size=16, num_layers=2,
                          num_heads=2, intermediate_size=32,
                          max_position=32, seq_len=8,
                          hidden_dropout=0.1, attention_dropout=0.1)
    ids, labels, loss = bert.build_pretrain_program(cfg)
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.amp = True
    s.layer_scan = layer_scan
    if tp_rules:
        s.tensor_parallel_degree = axes.get("tp", 1)
        s.tensor_parallel_rules = bert.tp_sharding_rules()
    if stage:
        s.sharding = True
        s.sharding_stage = stage
    if bucket_mb is not None:
        s.fuse_grad_size_in_mb = bucket_mb
    fleet.distributed_optimizer(
        paddle.optimizer.Adam(learning_rate=1e-4), s).minimize(loss)
    main = fluid.default_main_program()
    startup = fluid.default_startup_program()
    ndev = 1
    for v in axes.values():
        ndev *= v
    mesh = build_mesh(devices=jax.devices()[:ndev], **axes)
    rules = bert.tp_sharding_rules() if tp_rules else ShardingRules()
    attach(main, DistConfig(
        mesh=mesh, param_rules=rules,
        state_specs=dict(getattr(main, "_zero_state_specs", None) or {})))
    feed_shapes = {"input_ids": (batch, 8), "mlm_labels": (batch, 8, 1)}

    # PREDICT FIRST — before any Executor exists: the analysis itself
    # performs zero compiles (program metadata only)
    plan = analysis.PlanPoint(
        mesh_axes=dict(axes),
        param_rules=rules if tp_rules else None, batch=batch)
    rep = analysis.predict_cost(main, plan, fetch_names=[loss.name],
                                feed_shapes=feed_shapes,
                                with_findings=False)

    exe = fluid.Executor()
    exe.run(startup)
    feed = {"input_ids": np.zeros((batch, 8), np.int64),
            "mlm_labels": np.zeros((batch, 8, 1), np.int64)}
    txt = exe.compiled_hlo(feed, [loss])
    counts, byts = _audit.audit(txt)
    mem = exe.compiled_memory_analysis(feed, [loss])
    return {
        "mode": rep.mode, "exact": rep.exact,
        "predicted": {k: {"count": n, "bytes": b}
                      for k, (n, b) in rep.totals().items()},
        "measured": {k: {"count": int(counts[k]), "bytes": int(byts[k])}
                     for k in counts},
        "pred_mem": rep.memory,
        "meas_mem": {"arg": int(mem.argument_size_in_bytes),
                     "out": int(mem.output_size_in_bytes)},
    }

rows = {
    "dp2_repl": run_row({"dp": 2}),
    "dp2_zero1": run_row({"dp": 2}, stage=1),
    "dp2_zero2_bucketed": run_row({"dp": 2}, stage=2, bucket_mb=0.02),
    "dp2_zero3_rolled": run_row({"dp": 2}, stage=3, bucket_mb=0.02,
                                layer_scan=True),
    "dp4_repl": run_row({"dp": 4}, batch=32),
    "dp2_tp2": run_row({"dp": 2, "tp": 2}, tp_rules=True),
}
print(json.dumps(rows))
"""


def test_prediction_matches_census_and_memory():
    r = subprocess.run([sys.executable, "-c", textwrap.dedent(PARITY)],
                       env=cpu_mesh_env(8), capture_output=True,
                       text=True, timeout=900, cwd=REPO)
    assert r.returncode == 0, f"subprocess failed:\n{r.stdout}\n{r.stderr}"
    rows = json.loads(r.stdout.strip().splitlines()[-1])

    manual = ["dp2_repl", "dp2_zero1", "dp2_zero2_bucketed",
              "dp2_zero3_rolled", "dp4_repl"]
    for name in manual:
        row = rows[name]
        assert row["mode"] == "manual_dp", (name, row["mode"])
        assert row["exact"] is True, name
        pred, meas = row["predicted"], row["measured"]
        # kinds identical, counts EXACT, bytes within 1%
        assert set(pred) == set(meas), (name, pred, meas)
        for kind in meas:
            assert pred[kind]["count"] == meas[kind]["count"], \
                (name, kind, pred[kind], meas[kind])
            mb = meas[kind]["bytes"]
            assert abs(pred[kind]["bytes"] - mb) <= max(0.01 * mb, 0), \
                (name, kind, pred[kind], meas[kind])

    # the zero2 row must really exercise a K>1 bucket pipeline (several
    # RS/AG pairs), or the count-exactness above proved nothing
    z2 = rows["dp2_zero2_bucketed"]["measured"]
    assert z2["reduce-scatter"]["count"] >= 3, z2
    # and the rolled zero3 row the per-iteration gather + RNG state sync
    z3 = rows["dp2_zero3_rolled"]["measured"]
    assert z3["all-gather"]["count"] >= 5, z3
    assert z3["all-reduce"]["count"] >= 2, z3   # loss pmean + rng sync

    # memory: argument/output bytes within 5% on EVERY row (incl. dp×tp)
    for name, row in rows.items():
        am = row["meas_mem"]["arg"]
        ap = row["pred_mem"]["argument_bytes_per_device"]
        assert abs(ap - am) <= 0.05 * am, (name, ap, am)
        om = row["meas_mem"]["out"]
        op = row["pred_mem"]["output_bytes_per_device"]
        assert abs(op - om) <= 0.05 * om, (name, op, om)

    # GSPMD row: honestly flagged as an estimate, never claims kinds XLA
    # didn't emit
    tp = rows["dp2_tp2"]
    assert tp["mode"] == "gspmd" and tp["exact"] is False, tp
    assert set(tp["predicted"]) <= set(tp["measured"]), tp
